"""Headline benchmark (SURVEY.md §5). Trains the two BASELINE workloads on
the real chip and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baselines (BASELINE.json, reference-era P100 fp32 batch 64):
ResNet-50 ~200 img/s, Transformer base ~4500 tok/s. The headline metric is
the geometric-mean speedup over both; `value` is Transformer tok/s.

Defensive against a flaky hosted backend (round-1 failure mode: axon relay
init raised UNAVAILABLE and the whole run produced nothing): the TPU backend
is probed in a subprocess with retry/backoff before any in-process jax use,
each workload is independently try/excepted, and a JSON line is ALWAYS
printed — partial numbers (or a cpu-backend fallback) beat an empty round.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BASE_RESNET_IMG_S = 200.0
BASE_TRANSFORMER_TOK_S = 4500.0


def _probe_backend(attempts=2, first_backoff=10.0, attempt_timeout=45.0):
    """Probe TPU backend init in a SUBPROCESS (jax caches init failures
    in-process, so retrying there is useless; and a hung relay init must be
    killable). Returns the platform of the default backend ('tpu'/'axon')
    or 'cpu' after exhausting retries. Worst case ~100s (2 x 45s probes
    + 10s backoff) — r5 cut from ~240s: a healthy relay answers the
    device query in seconds, a down relay hangs past any timeout, so
    long probes only taxed the window (VERDICT r4 next-#1 'cut probe
    cost'); outages never resolved inside a retry window anyway.

    Returns (platform, degraded): degraded=True means retries were
    exhausted (flaky relay) as opposed to the machine genuinely defaulting
    to cpu (no TPU configured — a clean answer, not a fallback)."""
    probe = ("import jax; d = jax.devices(); "
             "print(d[0].platform if d else 'none')")
    backoff = first_backoff
    for i in range(attempts):
        try:
            r = subprocess.run([sys.executable, '-c', probe],
                               capture_output=True, text=True,
                               timeout=attempt_timeout)
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip().splitlines()[-1], False
            sys.stderr.write('bench: backend probe attempt %d/%d failed '
                             '(rc=%s): %s\n'
                             % (i + 1, attempts, r.returncode,
                                (r.stderr or '').strip()[-500:]))
        except subprocess.TimeoutExpired:
            sys.stderr.write('bench: backend probe attempt %d/%d timed '
                             'out after %.0fs\n'
                             % (i + 1, attempts, attempt_timeout))
        if i + 1 < attempts:
            time.sleep(backoff)
            backoff = min(backoff * 2, 120.0)
    return 'cpu', True


def _probe_quick(timeout=25.0):
    """Cheap is-the-relay-still-alive check between workloads: one tiny
    jitted matmul synced via np.asarray (the only true relay sync).
    Used after a workload failure so a mid-bench relay death stops the
    queue instead of burning every remaining watchdog on a dead chip
    (the r4 builder capture lost five 250-400s timeouts that way)."""
    probe = ("import jax, jax.numpy as jnp, numpy as np;"
             "x = jnp.ones((128, 128), jnp.bfloat16);"
             "np.asarray(jax.jit(lambda a: a @ a)(x).astype(jnp.float32));"
             "print('PROBE_OK')")
    try:
        r = subprocess.run([sys.executable, '-c', probe],
                           capture_output=True, text=True, timeout=timeout)
        return r.returncode == 0 and 'PROBE_OK' in (r.stdout or '')
    except subprocess.TimeoutExpired:
        return False


# ------------------------------------------------------------------ store
# Append-only per-workload results ledger shared by the driver bench run,
# tools/onchip_watcher.py, and ad-hoc builder runs (VERDICT r4 next-#1:
# "persist per-workload results incrementally to a resumable queue file").
# A bench run killed mid-queue loses nothing already measured; a later run
# (or the final JSON assembly) picks the freshest ok record per key.

def _store_path():
    return os.environ.get('BENCH_STORE', os.path.join(
        os.path.dirname(os.path.abspath(__file__)), 'ONCHIP_r05.jsonl'))


def _metrics_path():
    """Telemetry JSONL beside the results store: every workload child
    enables paddle_tpu.observe and appends its snapshots/summary here
    (pid-tagged lines), so each on-chip window leaves diagnosable
    numbers — compile seconds, cache hits, phase timings, MFU — not
    just the headline value. tools/metrics_report.py summarizes it."""
    env = os.environ.get('PADDLE_TPU_METRICS_JSONL')
    if env:
        return env
    root, _ = os.path.splitext(_store_path())
    return root + '_metrics.jsonl'


def store_put(key, workload, backend, value=None, ok=True, env=None,
              provenance='driver', error=None):
    rec = {'key': key, 'workload': workload, 'backend': backend,
           'ok': bool(ok), 'provenance': provenance,
           'ts': round(time.time(), 1)}
    if env:
        rec['env'] = env
    if ok:
        rec['value'] = value
    if error:
        rec['error'] = str(error)[:300]
    try:
        with open(_store_path(), 'a') as f:
            f.write(json.dumps(rec) + '\n')
    except OSError:
        pass
    return rec


def store_load(backends=('tpu', 'axon')):
    """Freshest ok record per key captured on a real chip. Torn lines
    (concurrent appends) are skipped per-line, never fatal."""
    out = {}
    try:
        with open(_store_path()) as f:
            for ln in f:
                try:
                    r = json.loads(ln)
                except ValueError:
                    continue
                if r.get('ok') and r.get('backend') in backends \
                        and r.get('key'):
                    prev = out.get(r['key'])
                    if prev is None or r.get('ts', 0) >= prev.get('ts', 0):
                        out[r['key']] = r
    except OSError:
        pass
    return out


def _fresh():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    return fluid


def _time_steps(run_step, warmup=3, iters=20):
    for _ in range(warmup):
        np.asarray(run_step()[0])  # np.asarray: the only true relay sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run_step()
    np.asarray(out[0])
    return (time.perf_counter() - t0) / iters


def _single_dispatch():
    # BENCH_SINGLE_DISPATCH=1 restores the one-dispatch-per-step loop
    # (the pre-round-3 measurement mode, kept as an ablation). Default
    # is Executor.run_steps: the training loop compiles INTO the XLA
    # program (lax.scan over steps), so per-dispatch overhead is paid
    # once per window — the intended TPU training loop, exactly
    # trajectory-equal to per-step dispatch (tests/test_executor.py).
    return os.environ.get('BENCH_SINGLE_DISPATCH') == '1'


def _time_multi(exe, feed, fetch, iters):
    """Per-step seconds using run_steps windows (one dispatch/window)."""
    out = exe.run_steps(iters, feed=feed, fetch_list=fetch,
                        return_numpy=False)
    arr = np.asarray(out[0])  # compile + warmup window
    if not np.isfinite(arr).all():
        raise RuntimeError('non-finite loss in warmup window')
    t0 = time.perf_counter()
    out = exe.run_steps(iters, feed=feed, fetch_list=fetch,
                        return_numpy=False)
    np.asarray(out[0])
    return (time.perf_counter() - t0) / iters


def _to_device(feed):
    import jax
    return {k: jax.device_put(v) for k, v in feed.items()}


def bench_transformer(batch=64, seq=64, vocab=32000, iters=20,
                      dropout=None, big=False):
    """dropout=None keeps each builder's canonical rate (base 0.1,
    big 0.3) — an explicit value is an override, not a default, so
    big=True cannot silently bench a lighter model."""
    fluid = _fresh()
    from paddle_tpu.models import transformer as T
    builder = T.transformer_big if big else T.transformer_base
    overrides = {} if dropout is None else {'dropout_rate': dropout}
    avg_cost, _ = builder(
        src_vocab_size=vocab, trg_vocab_size=vocab,
        src_seq_len=seq, trg_seq_len=seq,
        max_length=max(256, seq), **overrides)
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    # Device-resident feed: real input pipelines prefetch to HBM
    # (reader.prefetch_to_device); the bench measures the train step.
    feed = _to_device(T.make_fake_batch(batch, seq, seq, vocab, vocab))

    if not _single_dispatch():
        return batch * seq / _time_multi(exe, feed, [avg_cost], iters)

    def step():
        return exe.run(feed=feed, fetch_list=[avg_cost], return_numpy=False)

    dt = _time_steps(step, iters=iters)
    return batch * seq / dt


def _transformer_train_flops(batch, src_len, trg_len, vocab, n_layer=6,
                             n_head=8, d_key=64, d_model=512, d_inner=2048):
    """Analytic matmul FLOPs of one train step: fwd projections +
    attention einsums + FFN + logits, ×3 for backward (standard
    1 fwd + 2 bwd accounting; optimizer update is noise). Counted at
    the PADDED shapes — the dense work the hardware is asked to do —
    so MFU compares fairly across attention paths (a kernel that skips
    masked blocks shows up as >nominal utilization, which the
    mask_ratio field contextualizes)."""
    B, S, T = float(batch), float(src_len), float(trg_len)

    def proj(tokens, din, dout):
        return 2.0 * tokens * din * dout

    enc = n_layer * (
        4 * proj(B * S, d_model, d_model)             # q,k,v,o
        + 2 * 2.0 * B * n_head * S * S * d_key        # qkᵀ + p·v
        + 2 * proj(B * S, d_model, d_inner))          # both FFN mats
    dec = n_layer * (
        4 * proj(B * T, d_model, d_model)             # self q,k,v,o
        + 2 * 2.0 * B * n_head * T * T * d_key
        + 2 * proj(B * T, d_model, d_model)           # cross q,o
        + 2 * proj(B * S, d_model, d_model)           # cross k,v
        + 2 * 2.0 * B * n_head * T * S * d_key
        + 2 * proj(B * T, d_model, d_inner))
    logits = proj(B * T, d_model, vocab)
    return 3.0 * (enc + dec + logits)


def transformer_mfu_est(tok_s, batch=64, seq=64, vocab=32000):
    """THE MFU formula — shared by the headline detail and
    bench_trainspeed (ISSUE 19 satellite: one accounting path, not
    two). Analytic matmul FLOPs per token at the given shapes
    (:func:`_transformer_train_flops`) against the chip peak from
    ``observe.device_peak_flops`` (PADDLE_TPU_PEAK_TFLOPS /
    BENCH_PEAK_TFLOPS override it; 197 TFLOP/s — TPU v5e — when the
    device kind is unknown, preserving the old hand-rolled default)."""
    from paddle_tpu import observe
    flops_per_tok = _transformer_train_flops(batch, seq, seq, vocab) \
        / (batch * seq)
    peak = observe.device_peak_flops()
    if peak is None:
        peak = float(os.environ.get('BENCH_PEAK_TFLOPS', '197')) * 1e12
    return tok_s * flops_per_tok / peak


def bench_transformer_masked(batch=8, seq=512, vocab=32000, iters=10):
    """Masked co-headline (VERDICT r4 next-#4): a variable-length batch
    at seq 512 — the actual NMT workload shape, where attention matters
    and rows carry real padding. src lengths drawn uniform [seq/2, seq];
    lbl_weight masks the same rows so the loss is honest. Reports padded
    tok/s (comparable to the seq-64 headline), real tok/s, and MFU from
    analytic matmul FLOPs vs the chip's bf16 peak (BENCH_PEAK_TFLOPS,
    default 197 — TPU v5e)."""
    fluid = _fresh()
    from paddle_tpu.models import transformer as T
    avg_cost, _ = T.transformer_base(
        src_vocab_size=vocab, trg_vocab_size=vocab,
        src_seq_len=seq, trg_seq_len=seq, max_length=max(512, seq))
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = T.make_fake_batch(batch, seq, seq, vocab, vocab)
    lens = rng.randint(seq // 2, seq + 1, (batch,)).astype('int64')
    feed['src_length'] = lens
    feed['lbl_weight'] = (np.arange(seq)[None, :] <
                          lens[:, None]).astype('float32')
    feed = _to_device(feed)
    dt = _time_multi(exe, feed, [avg_cost], iters)
    flops = _transformer_train_flops(batch, seq, seq, vocab)
    peak = float(os.environ.get('BENCH_PEAK_TFLOPS', '197')) * 1e12
    return {'tok_per_sec': round(batch * seq / dt, 1),
            'real_tok_per_sec': round(float(lens.sum()) / dt, 1),
            'mask_ratio': round(float(lens.sum()) / (batch * seq), 3),
            'analytic_tflops_per_step': round(flops / 1e12, 3),
            'mfu': round(flops / dt / peak, 4),
            'attention_path': 'pallas' if os.environ.get(
                'PADDLE_TPU_USE_PALLAS') == '1' else 'xla'}


def bench_moe(batch=32, seq=64, vocab=32000, num_experts=8,
              capacity_factor=1.25, n_layer=4, iters=10):
    """Switch-MoE LM train throughput (tokens/s) — the ep-axis flagship
    measured on one chip (routing + capacity dispatch overhead vs the
    dense transformer). The capacity-factor sweep ablation quantifies
    the drop-rate/throughput trade the Switch paper tunes."""
    fluid = _fresh()
    from paddle_tpu.models.moe import switch_transformer_lm
    # scan_layers: the unrolled 4-block MoE graph was the one workload
    # that out-compiled its watchdog on the relay (250 s timeouts, r4
    # capture); the moe_layer_stack scan compiles flat over depth
    avg_cost, _ = switch_transformer_lm(
        vocab_size=vocab, seq_len=seq, n_layer=n_layer, n_head=8,
        d_model=512, d_inner=2048, num_experts=num_experts,
        capacity_factor=capacity_factor, dropout_rate=0.1,
        max_length=max(512, seq),
        scan_layers=os.environ.get('BENCH_MOE_SCAN', '1') != '0')
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    words = rng.randint(1, vocab, (batch, seq)).astype('int64')
    feed = _to_device({'word': words,
                       'label': np.roll(words, -1, axis=1)})
    return batch * seq / _time_multi(exe, feed, [avg_cost], iters)


def bench_rnn_lstm(batch=128, seq=100, vocab=30000, hidden=128,
                   lstm_num=1, iters=20):
    """The reference benchmark/paddle/rnn/rnn.py config (stacked-LSTM
    IMDB sentiment), built VERBATIM through the v1
    trainer_config_helpers shim — the rnn/ half of the benchmark suite
    beside image/. Reports tokens/s (batch*seq / step)."""
    fluid = _fresh()
    from paddle_tpu.trainer_config_helpers import (
        AdamOptimizer, L2Regularization, SoftmaxActivation,
        classification_cost, data_layer, embedding_layer, fc_layer,
        last_seq, settings, simple_lstm)
    net = data_layer('data', size=vocab, dtype='int64', seq_type=1)
    net = embedding_layer(input=net, size=128)
    for _ in range(lstm_num):
        net = simple_lstm(input=net, size=hidden)
    net = last_seq(input=net)
    net = fc_layer(input=net, size=2, act=SoftmaxActivation())
    lab = data_layer('label', 1, dtype='int64')
    loss = classification_cost(input=net, label=lab)
    settings(batch_size=batch, learning_rate=2e-3,
             learning_method=AdamOptimizer(),
             regularization=L2Regularization(8e-4),
             gradient_clipping_threshold=25).minimize(loss)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = _to_device({
        'data': rng.randint(1, vocab, (batch, seq)).astype('int64'),
        'data_len': np.full((batch,), seq, 'int32'),
        'label': rng.randint(0, 2, (batch, 1)).astype('int64')})
    return batch * seq / _time_multi(exe, feed, [loss], iters)


def bench_pipeline_ablation(model='transformer', steps=20, batch=None,
                            seq=64, vocab=32000, image=224,
                            depths=(1, 2, 4)):
    """Sync-vs-async trainer loop (ISSUE 4): the same HOST-FED workload
    through Trainer.train at pipeline_depth 1/2/4. Unlike the headline
    bench (device-resident feed, run_steps windows), every step here
    pays reader iteration + _to_feed + h2d + metric fetch — exactly the
    overheads the pipelined loop overlaps with device compute. Epoch 0
    warms the compile cache; epoch 1 is timed. Reports per-depth
    throughput plus the measured overlap fraction
    (1 - (host_blocked + device_blocked)/wall over the timed epoch),
    which also lands in the metrics JSONL as gauges."""
    import time as _t
    from paddle_tpu import observe as _observe
    import paddle_tpu.trainer as _trmod

    out = {'model': model, 'steps_per_epoch': steps}
    for d in depths:
        fluid = _fresh()
        if model == 'transformer':
            from paddle_tpu.models import transformer as T
            b = batch or 64

            def train_func():
                avg_cost, _ = T.transformer_base(
                    src_vocab_size=vocab, trg_vocab_size=vocab,
                    src_seq_len=seq, trg_seq_len=seq,
                    max_length=max(256, seq))
                return [avg_cost]

            def reader():
                for i in range(steps):
                    yield T.make_fake_batch(b, seq, seq, vocab, vocab,
                                            seed=i)

            unit = b * seq
            opt = lambda: fluid.optimizer.Adam(learning_rate=1e-4)
        else:
            from paddle_tpu.models.resnet import resnet50_with_loss
            b = batch or 64

            def train_func():
                _, avg_cost, _ = resnet50_with_loss()
                return [avg_cost]

            def reader():
                rng = np.random.RandomState(0)
                for i in range(steps):
                    yield {'image': rng.rand(b, 3, image,
                                             image).astype('float32'),
                           'label': rng.randint(
                               0, 1000, (b, 1)).astype('int64')}

            unit = b
            opt = lambda: fluid.optimizer.Momentum(learning_rate=0.1,
                                                   momentum=0.9)

        state = {}

        def handler(e, state=state):
            if isinstance(e, _trmod.BeginEpochEvent) and e.epoch == 1:
                state['hb0'] = _observe.get_gauge(
                    'trainer.host_blocked_seconds') or 0.0
                state['db0'] = _observe.get_gauge(
                    'trainer.device_blocked_seconds') or 0.0
                state['t0'] = _t.perf_counter()
            elif isinstance(e, _trmod.EndEpochEvent) and e.epoch == 1:
                state['t1'] = _t.perf_counter()
                state['hb1'] = _observe.get_gauge(
                    'trainer.host_blocked_seconds') or 0.0
                state['db1'] = _observe.get_gauge(
                    'trainer.device_blocked_seconds') or 0.0

        trainer = fluid.Trainer(train_func=train_func,
                                optimizer_func=opt,
                                place=fluid.TPUPlace(0))
        trainer.program.amp = 'bf16'
        trainer.train(num_epochs=2, event_handler=handler, reader=reader,
                      pipeline_depth=d,
                      host_prefetch=(2 if d > 1 else 0))
        wall = state['t1'] - state['t0']
        key = 'd%d' % d
        out[key + '_per_sec'] = round(unit * steps / wall, 1)
        if _observe.enabled():
            overlap = max(0.0, 1.0 - (
                (state['hb1'] - state['hb0']) +
                (state['db1'] - state['db0'])) / wall)
            out[key + '_overlap'] = round(overlap, 4)
            # into the metrics JSONL so the on-chip watcher's relay
            # runs capture it beside the throughput rows
            _observe.set_gauge('bench.pipeline_overlap_fraction',
                               overlap, model=model, depth=d)
            _observe.set_gauge('bench.pipeline_per_sec',
                               out[key + '_per_sec'], model=model,
                               depth=d)
    if out.get('d1_per_sec'):
        for d in depths[1:]:
            k = 'd%d_per_sec' % d
            if out.get(k):
                out['async_speedup_d%d' % d] = round(
                    out[k] / out['d1_per_sec'], 3)
    return out


def bench_decode(duration=8.0, clients=8, max_batch=16, block_size=32,
                 num_blocks=512, pages_per_seq=16, vocab=8000, n_layer=4,
                 n_head=8, d_model=256, d_inner=512, prompt_lo=16,
                 prompt_hi=64, max_new=64, shared_prefix=0.95,
                 shared_prefix_len=None, spec_k=3):
    """Decode-serving scenario: continuous batching + paged KV cache
    (serving/decode) under closed-loop streaming clients, on the
    fleet-realistic traffic mix (``shared_prefix`` of requests open
    with one shared system prompt). Two legs ablate speculative
    decoding off/on over the global prefix cache; cache-hit-rate,
    prefill-tokens-skipped, and accepted-draft-length land in the
    metrics JSONL (decode.prefix_* / decode.spec_*) beside tokens/sec
    and inter-token latency."""
    import threading

    from paddle_tpu import observe
    from paddle_tpu.serving.decode import DecodeEngine, LMSpec
    from paddle_tpu.serving.loadgen import Stats, closed_loop, percentiles

    d_head = max(8, d_model // n_head)
    spec = LMSpec(vocab_size=vocab, n_layer=n_layer, n_head=n_head,
                  d_key=d_head, d_value=d_head, d_model=d_model,
                  d_inner=d_inner)
    capacity = pages_per_seq * block_size
    prompt_hi = min(prompt_hi, capacity - max_new)
    n_shared = shared_prefix_len or max(block_size,
                                        (prompt_lo + prompt_hi) // 2)
    n_shared = min(n_shared, max(1, prompt_hi - 1))
    shared_ids = np.random.RandomState(1234).randint(
        0, vocab, n_shared).tolist()

    def counter_delta(after, before, name):
        return after['counters'].get(name, 0) - \
            before['counters'].get(name, 0)

    def run_leg(leg_spec_k):
        engine = DecodeEngine(spec, max_batch=max_batch,
                              block_size=block_size,
                              num_blocks=num_blocks,
                              pages_per_seq=pages_per_seq,
                              max_queue_depth=4 * clients,
                              prefix_cache=True, spec_k=leg_spec_k)
        t_w0 = time.time()
        signatures = engine.warmup()
        warmup_s = time.time() - t_w0
        engine.start()

        stats = Stats()
        gaps, tokens = [], [0]
        mu = threading.Lock()

        def do_request(rng):
            plen = int(rng.randint(prompt_lo, prompt_hi + 1))
            if rng.rand() < shared_prefix:
                tail = max(1, plen - n_shared)
                prompt = shared_ids + \
                    rng.randint(0, vocab, tail).tolist()
            else:
                prompt = rng.randint(0, vocab, plen).tolist()
            stream = engine.submit(prompt, max_new_tokens=max_new)
            n, t_prev, local = 0, None, []
            for _tok in stream:
                now = time.perf_counter()
                if t_prev is not None:
                    local.append(now - t_prev)
                t_prev = now
                n += 1
            with mu:
                gaps.extend(local)
                tokens[0] += n
            return n

        before = observe.snapshot()
        t0 = time.perf_counter()
        closed_loop(do_request, stats, t0 + duration, clients)
        engine.shutdown(drain=True)
        wall = time.perf_counter() - t0
        snap = observe.snapshot()
        occ = snap['histograms'].get('decode.batch_occupancy', {})
        acc = snap['histograms'].get('decode.spec_accepted_len', {})
        tps = tokens[0] / wall if wall else 0.0
        hit = counter_delta(
            snap, before,
            'decode.prefix_cache_lookups_total{outcome=hit}')
        miss = counter_delta(
            snap, before,
            'decode.prefix_cache_lookups_total{outcome=miss}')
        spec_steps = counter_delta(snap, before,
                                   'decode.spec_steps_total')
        accepted = counter_delta(snap, before,
                                 'decode.spec_accepted_tokens_total')
        return {
            'spec_k': leg_spec_k,
            'tokens_per_s': round(tps, 2),
            'tokens': tokens[0],
            'requests_ok': stats.ok,
            'duration_s': round(wall, 3),
            'inter_token_ms': percentiles(gaps),
            'request_ms': percentiles(stats.latencies),
            'batch_occupancy_mean': occ.get('mean'),
            'preemptions': counter_delta(snap, before,
                                         'decode.preemptions_total'),
            'cache_hit_rate': round(hit / float(hit + miss), 4)
            if (hit + miss) else None,
            'prefill_tokens_skipped': counter_delta(
                snap, before, 'decode.prefix_tokens_reused_total'),
            'accepted_draft_len_mean': acc.get('mean')
            if spec_steps else None,
            'accepted_draft_len_p50': acc.get('p50')
            if spec_steps else None,
            'accepted_tokens_total': accepted,
            'warmup': {'signatures': signatures,
                       'seconds': round(warmup_s, 3)},
        }

    legs = {'spec_off': run_leg(0)}
    if spec_k:
        legs['spec_on'] = run_leg(spec_k)
    head = legs.get('spec_on') or legs['spec_off']
    observe.set_gauge('decode.bench_tokens_per_s',
                      head['tokens_per_s'])
    out = dict(head)
    out.update({
        'workload': 'decode_transformer',
        'shared_prefix': shared_prefix,
        'shared_prefix_len': n_shared,
        'spec_ablation': legs,
        'spec_speedup': round(
            legs['spec_on']['tokens_per_s'] /
            legs['spec_off']['tokens_per_s'], 3)
        if 'spec_on' in legs and legs['spec_off']['tokens_per_s']
        else None,
        'engine': {'max_batch': max_batch, 'block_size': block_size,
                   'num_blocks': num_blocks,
                   'pages_per_seq': pages_per_seq},
        'model': {'vocab': vocab, 'n_layer': n_layer, 'n_head': n_head,
                  'd_model': d_model},
    })
    return out


class _ChaosPredictor(object):
    """Duck-typed predictor with a fixed per-batch compute floor: the
    overload arithmetic (offered rows/s vs replica capacity) stops
    depending on how fast THIS machine's tiny MLP runs, so chaos
    windows burn error budget by construction. Shared by the fleet and
    autoscale chaos workloads."""

    def __init__(self, inner, delay_s):
        self._inner = inner
        self._delay_s = delay_s

    def __getattr__(self, attr):
        return getattr(self._inner, attr)

    def predict(self, feed):
        out = self._inner.predict(feed)
        if self._delay_s:
            time.sleep(self._delay_s)
        return out


def _save_chaos_model(in_dim):
    """Save the tiny MLP the chaos scenarios serve; returns its dir."""
    import tempfile
    fluid = _fresh()
    model_dir = os.path.join(tempfile.mkdtemp(prefix='fleet_bench_'),
                             'model')
    x = fluid.layers.data(name='x', shape=[in_dim], dtype='float32')
    h = fluid.layers.fc(input=x, size=16, act='relu')
    out = fluid.layers.fc(input=h, size=4, act='softmax')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(model_dir, ['x'], [out], exe)
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    return model_dir


def bench_fleet(replicas=3, duration=6.0, steady_qps=40.0,
                spike_qps=700.0, spike_at=2.0, spike_s=1.5, kill_at=2.4,
                latency_budget_s=0.025, availability=0.95, window_s=1.5,
                max_batch=8, max_queue_depth=12, trace_sample=0.05,
                in_dim=8, retries=3, compute_delay_ms=10.0):
    """Fleet chaos scenario (ROADMAP item 5): a >=3-replica router under
    a diurnal open-loop load with a flash-crowd burst and a replica
    kill mid-spike (fault.inject.kill_replica). Asserts nothing itself
    — it measures and returns: accepted/completed/lost request counts
    (the zero-loss contract), the burn-rate and goodput timelines
    around the kill window, per-phase reject/error counts (plottable
    shed windows), readiness flips, and the sampled-trace census.
    slo.*/router.* metrics land in the metrics JSONL beside the
    results store; tools/metrics_report.py --slo renders them."""
    import threading

    from paddle_tpu import observe
    from paddle_tpu.fault import inject
    from paddle_tpu.observe.slo import Objective, SloTracker
    from paddle_tpu.serving import (NoReplicaAvailableError, Router,
                                    ServingEngine)
    from paddle_tpu.serving.loadgen import (Stats, diurnal, flash_crowd,
                                            heavy_tailed_rows, open_loop,
                                            percentiles)

    model_dir = _save_chaos_model(in_dim)
    from paddle_tpu.inference import create_predictor

    delay_s = float(compute_delay_ms) / 1000.0
    engines = [ServingEngine(_ChaosPredictor(create_predictor(model_dir),
                                             delay_s),
                             max_batch_size=max_batch,
                             batch_timeout_ms=1.0,
                             max_queue_depth=max_queue_depth,
                             name='replica%d' % i)
               for i in range(replicas)]
    t_w0 = time.perf_counter()
    for eng in engines:
        eng.warmup()
        eng.start()
    warmup_s = time.perf_counter() - t_w0

    tracker = SloTracker([Objective('fleet', latency_budget_s,
                                    availability_target=availability,
                                    window_s=window_s)])
    router = Router(engines, slo=tracker, route='fleet',
                    retries=retries)

    schedule = flash_crowd(
        diurnal(steady_qps, 1.25 * steady_qps, period_s=2 * duration),
        spike_qps, spike_at, spike_s)

    stats = Stats()
    submitted = [0]
    no_replica = [0]

    def submit_request(rng):
        rows = heavy_tailed_rows(rng, 1, max_batch)
        feed = {'x': rng.rand(rows, in_dim).astype('float32')}
        try:
            fut = router.submit(feed, session=int(rng.randint(0, 64)),
                                deadline_s=latency_budget_s)
        except NoReplicaAvailableError:
            no_replica[0] += 1
            return None   # counted as a reject in the ledger
        # QueueFullError (incl. SLOShedError) propagates: the loop
        # counts it as a reject with a timestamp
        submitted[0] += 1
        return fut, rows

    victim = engines[-1]
    ready_before_kill = [None]
    ready_after_kill = [None]
    burn_timeline, goodput_timeline = [], []
    t0 = time.perf_counter()
    stop = threading.Event()

    def sampler():
        while not stop.wait(0.05):
            now = time.perf_counter()
            burn_timeline.append(
                (round(now - t0, 3), tracker.burn_rate('fleet', now)))
            goodput_timeline.append(
                (round(now - t0, 3), tracker.goodput('fleet', now)))

    def killer():
        wait = kill_at - (time.perf_counter() - t0)
        if wait > 0:
            time.sleep(wait)
        ready_before_kill[0] = victim.ready()
        inject.kill_replica(victim, drain=False)
        ready_after_kill[0] = victim.ready()

    threads = [threading.Thread(target=sampler, daemon=True),
               threading.Thread(target=killer, daemon=True)]
    # sampled requests leave cross-thread trace timelines + exemplars;
    # per-call env read, restored after the run
    prev_sample = os.environ.get('PADDLE_TPU_TRACE_SAMPLE')
    os.environ['PADDLE_TPU_TRACE_SAMPLE'] = str(trace_sample)
    try:
        for t in threads:
            t.start()
        open_loop(submit_request, stats, t0 + duration, schedule)
        for eng in engines:
            if eng is not victim:
                eng.shutdown(drain=True)
        # router callbacks resolve synchronously with the inner
        # futures; a short grace covers the last callback chain
        t_end = time.perf_counter() + 10.0
        while stats.ok + stats.errors < submitted[0] and \
                time.perf_counter() < t_end:
            time.sleep(0.01)
    finally:
        stop.set()
        if prev_sample is None:
            os.environ.pop('PADDLE_TPU_TRACE_SAMPLE', None)
        else:
            os.environ['PADDLE_TPU_TRACE_SAMPLE'] = prev_sample
    wall = time.perf_counter() - t0
    for t in threads:
        t.join(timeout=5)
    router.close()
    tracker.publish()

    # sampled-trace census: distinct trace ids and the widest thread
    # spread any one of them achieved (the >=3-thread acceptance)
    by_trace = {}
    for ev in observe.spans().events():
        tid = (ev.get('args') or {}).get('trace_id')
        if tid and ev.get('ph') == 'X':
            by_trace.setdefault(tid, set()).add(ev.get('tid'))
    kill_window = (kill_at, min(kill_at + 2.0, duration))
    burn_during_kill = max(
        [b for t, b in burn_timeline
         if kill_window[0] <= t <= kill_window[1]] or [0.0])
    tail = [g for t, g in goodput_timeline if t >= 0.8 * duration]
    accepted = submitted[0]
    completed = stats.ok + stats.errors
    phases = {
        'steady': stats.counts_between(0.0, spike_at),
        'spike': stats.counts_between(spike_at, spike_at + spike_s),
        'after': stats.counts_between(spike_at + spike_s, duration),
    }
    snap = observe.snapshot()
    return {
        'workload': 'fleet',
        'replicas': replicas,
        'duration_s': round(wall, 3),
        'accepted': accepted,
        'completed': completed,
        'lost': accepted - completed,
        'requests_ok': stats.ok,
        'requests_rejected': stats.rejected,
        'requests_errored': stats.errors,
        'no_replica': no_replica[0],
        'latency_ms': percentiles(stats.latencies),
        'phases': phases,
        'burn_during_kill': round(burn_during_kill, 4),
        'burn_timeline': burn_timeline,
        'goodput_end_rps': round(sum(tail) / len(tail), 2)
        if tail else 0.0,
        'goodput_timeline': goodput_timeline,
        'kill': {'victim': victim.name, 'at_s': kill_at,
                 'ready_before': ready_before_kill[0],
                 'ready_after': ready_after_kill[0]},
        'failovers': sum(
            v for k, v in snap['counters'].items()
            if k.startswith('router.failover_total')),
        'sheds': sum(v for k, v in snap['counters'].items()
                     if k.startswith('router.shed_total')),
        'sampled_traces': len(by_trace),
        'max_trace_threads': max(
            [len(tids) for tids in by_trace.values()] or [0]),
        'slo': {'route': 'fleet',
                'latency_budget_s': latency_budget_s,
                'availability_target': availability,
                'window_s': window_s},
        'warmup_s': round(warmup_s, 3),
    }


def bench_quant(dp=8, steps=150, hidden=256, in_dim=64,
                kv_duration=2.5, kv_block_size=8, kv_pages_per_seq=8,
                kv_blocks_fp32=16, fleet_ab=True, fleet_duration=4.0,
                reduced=False):
    """Quantization ablation (ISSUE 13), three asserted legs:

    1. **int8 gradient allreduce** — the same MLP regression trained
       twice on a dp mesh, fp32 vs quantized grads
       (ParallelStrategy(quantized_allreduce=True)); asserts the
       simulated dp comm bytes drop >= 3x (quant.allreduce_* gauges
       from the executor's wire model) with final-loss delta within
       tolerance, off-leg bit-identical to baseline, and the REAL
       shard_map quantized_all_reduce within rel-err of exact psum.
    2. **quantized KV arena** — equal ARENA BYTES, fp32 pages vs the
       int8 pages that budget buys; closed-loop decode load measures
       resident_seqs_peak on each (assert >= 1.8x), decode outputs
       pass the parity bound (paged-attention cosine vs fp32 + token
       agreement), and kv_dtype off is bit-identical to default.
    3. **fleet A/B** — the chaos fleet scenario with baseline replicas
       vs 'quantized' replicas whose per-replica concurrency ceiling
       is scaled by the capacity ratio leg 2 MEASURED (decode replicas
       are HBM-bound: resident sequences == batch ceiling) — goodput
       and burn rate under the same flash-crowd + kill schedule, so
       the win is judged on fleet SLOs, not microbenchmarks.
    """
    import jax
    import jax.numpy as jnp

    from paddle_tpu import observe, quant
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import (ParallelStrategy,
                                                transpile)

    out = {'workload': 'quant'}
    dp = max(1, min(int(dp), jax.device_count()))

    # ---- leg 1: int8 gradient allreduce on the trainer path --------
    def train_leg(quant_on):
        fluid = _fresh()
        np.random.seed(0)
        true_w = np.random.randn(in_dim, 1).astype('float32')
        x = fluid.layers.data(name='x', shape=[in_dim], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=hidden, act='relu',
                            param_attr=fluid.ParamAttr(name='q_w1'))
        h = fluid.layers.fc(input=h, size=64, act='relu')
        pred = fluid.layers.fc(input=h, size=1, act=None)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.02).minimize(cost)
        if dp > 1:
            transpile(fluid.default_main_program(), make_mesh(dp=dp),
                      ParallelStrategy(data_parallel=True,
                                       quantized_allreduce=quant_on))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses = []
        for _ in range(steps):
            xs = np.random.randn(8 * dp, in_dim).astype('float32')
            ys = xs @ true_w
            got = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[cost])
            losses.append(float(np.asarray(got[0]).reshape(())))
        w1 = np.asarray(fluid.global_scope().find('q_w1'))
        return losses, w1

    loss_f, w_f = train_leg(False)
    loss_f2, w_f2 = train_leg(False)     # off-leg determinism baseline
    loss_q, w_q = train_leg(True)
    snap = observe.snapshot()
    g = snap['gauges']
    bytes_fp32 = g.get('quant.allreduce_bytes_fp32', 0)
    bytes_quant = g.get('quant.allreduce_bytes_quant', 1)
    compression = g.get('quant.allreduce_compression', 0)
    loss_delta = abs(loss_q[-1] - loss_f[-1])
    loss_tol = max(0.05, 0.25 * abs(loss_f[-1]))
    assert np.array_equal(w_f, w_f2), \
        'quantized_allreduce=False must stay bit-identical run to run'
    if dp > 1:
        assert compression >= 3.0, \
            'int8 allreduce compression %.2fx < 3x' % compression
        assert loss_delta <= loss_tol, \
            'quantized final loss %.4f vs fp32 %.4f (tol %.4f)' \
            % (loss_q[-1], loss_f[-1], loss_tol)

    # the REAL two-leg schedule vs exact psum, over the same mesh
    qar = {'dp': dp}
    if dp > 1:
        from jax.sharding import Mesh, PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        from paddle_tpu.parallel import collective
        mesh = Mesh(np.array(jax.devices()[:dp]).reshape(dp), ('dp',))
        xs = np.random.RandomState(1).randn(dp, 1 << 14) \
            .astype('float32')
        f = shard_map(
            lambda a: collective.quantized_all_reduce(
                a.reshape(-1), 'dp',
                key=jax.random.PRNGKey(3)).reshape(a.shape),
            mesh=mesh, in_specs=(P('dp', None),),
            out_specs=P('dp', None))
        got = np.asarray(jax.jit(f)(xs))
        exact = np.tile(xs.sum(0, keepdims=True), (dp, 1))
        rel = float(np.abs(got - exact).max() / np.abs(exact).max())
        assert rel < 0.05, 'quantized_all_reduce rel err %.4f' % rel
        qar['rel_err_vs_psum'] = round(rel, 6)
    out['allreduce'] = {
        'dp': dp, 'steps': steps,
        'final_loss_fp32': round(loss_f[-1], 6),
        'final_loss_int8': round(loss_q[-1], 6),
        'loss_delta': round(loss_delta, 6),
        'bytes_fp32_per_step': bytes_fp32,
        'bytes_int8_per_step': bytes_quant,
        'compression_x': round(compression, 3),
        'collective': qar,
        'off_leg_bit_identical': True,
    }
    observe.set_gauge('quant.bench_allreduce_compression', compression)

    # ---- leg 2: quantized KV arena at equal bytes ------------------
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention_reference)
    from paddle_tpu.serving.decode import (DecodeEngine, LMSpec,
                                           random_weights)
    from paddle_tpu.serving.decode.model import (arena_bytes,
                                                 kv_bytes_per_token,
                                                 num_blocks_for_budget)
    from paddle_tpu.serving.loadgen import Stats, closed_loop

    spec = LMSpec(vocab_size=256, n_layer=2, n_head=2, d_key=16,
                  d_value=16, d_model=32, d_inner=64)
    weights = random_weights(spec, seed=3)
    budget = arena_bytes(spec, kv_blocks_fp32, kv_block_size, 'float32')
    nb_int8 = num_blocks_for_budget(budget, spec, kv_block_size, 'int8')
    capacity_ratio = nb_int8 / float(kv_blocks_fp32)

    def kv_leg(kv_dtype, num_blocks):
        eng = DecodeEngine(spec, max_batch=12, block_size=kv_block_size,
                           num_blocks=num_blocks,
                           pages_per_seq=kv_pages_per_seq,
                           max_queue_depth=64, weights=weights,
                           kv_dtype=kv_dtype)
        eng.warmup()
        eng.start()
        stats = Stats()

        def do_request(rng):
            plen = int(rng.randint(16, 25))
            prompt = rng.randint(0, 256, plen).tolist()
            return len(eng.submit(prompt, max_new_tokens=24)
                       .result(120))

        closed_loop(do_request, stats,
                    time.perf_counter() + kv_duration, 10)
        eng.shutdown(drain=True)
        return {'kv_dtype': eng.kv_dtype, 'num_blocks': num_blocks,
                'arena_bytes': arena_bytes(spec, num_blocks,
                                           kv_block_size, eng.kv_dtype),
                'kv_bytes_per_token': eng.kv_bytes_per_token,
                'resident_seqs_peak': eng.resident_seqs_peak,
                'requests_ok': stats.ok}

    leg_f = kv_leg('fp32', kv_blocks_fp32)
    leg_q = kv_leg('int8', nb_int8)
    resident_ratio = leg_q['resident_seqs_peak'] / \
        max(1.0, leg_f['resident_seqs_peak'])
    assert leg_q['arena_bytes'] <= budget, 'equal-bytes violated'
    assert resident_ratio >= 1.8, \
        'resident seqs %.2fx < 1.8x at equal arena bytes (fp32 peak ' \
        '%d, int8 peak %d)' % (resident_ratio,
                               leg_f['resident_seqs_peak'],
                               leg_q['resident_seqs_peak'])

    # parity bound: the dequantized attention path vs fp32, and token
    # agreement between fp32/int8 engines on identical prompts
    rng = np.random.RandomState(7)
    nb, h_, bs, d = 8, 2, kv_block_size, 16
    kf = rng.randn(nb, h_, bs, d).astype('float32')
    vf = rng.randn(nb, h_, bs, d).astype('float32')
    kq, ks = quant.quantize_rows(jnp.asarray(kf), 'int8')
    vq, vs = quant.quantize_rows(jnp.asarray(vf), 'int8')
    q = rng.randn(3, h_, d).astype('float32')
    tables = np.array([[0, 1, 2, 7], [3, 4, 8, 8], [5, 6, 8, 8]],
                      'int32')
    lens = np.array([4 * bs - 2, 2 * bs, bs + 3], 'int32')
    ref = np.asarray(paged_attention_reference(q, kf, vf, tables, lens))
    got = np.asarray(paged_attention_reference(
        q, np.asarray(kq), np.asarray(vq), tables, lens,
        k_scales=np.asarray(ks), v_scales=np.asarray(vs)))
    cos = float((ref * got).sum() /
                (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-12))
    assert cos >= 0.99, 'paged-attention parity cosine %.5f' % cos

    def token_streams(kv_dtype):
        eng = DecodeEngine(spec, max_batch=4, block_size=kv_block_size,
                           num_blocks=kv_blocks_fp32,
                           pages_per_seq=kv_pages_per_seq,
                           weights=weights, kv_dtype=kv_dtype)
        eng.start()
        prng = np.random.RandomState(11)
        outs = [eng.generate(prng.randint(0, 256, 12).tolist(),
                             max_new_tokens=12, timeout=120)
                for _ in range(6)]
        eng.shutdown()
        return outs

    tok_f = token_streams('fp32')
    tok_default = token_streams(None)      # knob off == fp32, bit-exact
    tok_q = token_streams('int8')
    assert tok_f == tok_default, 'kv_dtype off must be bit-identical'
    agree = []
    for a, b in zip(tok_f, tok_q):
        n = sum(1 for t_a, t_b in zip(a, b) if t_a == t_b)
        agree.append(n / float(max(len(a), 1)))
    token_match = float(np.mean(agree))
    out['kv'] = {
        'arena_budget_bytes': budget,
        'fp32': leg_f, 'int8': leg_q,
        'capacity_ratio_pages': round(capacity_ratio, 3),
        'resident_seqs_ratio': round(resident_ratio, 3),
        'parity': {'attention_cosine': round(cos, 6),
                   'token_match_mean': round(token_match, 4)},
        'off_bit_identical': True,
    }
    observe.set_gauge('quant.bench_kv_resident_ratio', resident_ratio)
    observe.set_gauge('quant.bench_kv_parity_cosine', cos)

    # ---- leg 3: fleet A/B on goodput + burn rate -------------------
    if fleet_ab:
        fleet_kw = dict(duration=fleet_duration, steady_qps=30.0,
                        spike_qps=500.0, spike_at=1.0, spike_s=1.0,
                        kill_at=1.2, window_s=1.0, max_queue_depth=10)
        base = bench_fleet(max_batch=8, **fleet_kw)
        # quantized replicas: the measured KV capacity ratio raises the
        # per-replica concurrency ceiling (decode replicas are
        # HBM-bound — resident sequences ARE the batch ceiling)
        q_batch = int(round(8 * min(resident_ratio, 2.5)))
        quant_leg = bench_fleet(max_batch=q_batch, **fleet_kw)

        def trim(r):
            return {k: r[k] for k in
                    ('accepted', 'completed', 'lost', 'requests_ok',
                     'requests_rejected', 'goodput_end_rps',
                     'burn_during_kill', 'latency_ms')}

        assert base['lost'] == 0 and quant_leg['lost'] == 0
        out['fleet_ab'] = {
            'baseline_max_batch': 8,
            'quantized_max_batch': q_batch,
            'baseline': trim(base),
            'quantized': trim(quant_leg),
            'goodput_delta_rps': round(
                quant_leg['goodput_end_rps'] - base['goodput_end_rps'],
                2),
            'burn_delta': round(quant_leg['burn_during_kill'] -
                                base['burn_during_kill'], 4),
        }
    return out


def bench_trainspeed(dp=8, steps=24, hidden=64, in_dim=32, batch=8,
                     overlap_iters=6, fp8_n=64, mfu_batch=2, mfu_seq=16,
                     mfu_vocab=512, mfu_iters=3, reduced=False):
    """Training raw speed (ISSUE 19), asserted legs:

    1. **bucketed exact allreduce** — the same dyadic MLP+SGD
       regression trained unbucketed vs bucketed
       (ParallelStrategy(grad_bucket_mb=...)) on the dp CPU mesh;
       asserts final params BIT-IDENTICAL (the exact path is a pure
       relayout) and >= 2 buckets formed (trainer.grad_bucket_count).
    2. **backward/allreduce overlap** — three-point estimate
       (observe.overlap_fraction): bucketed step vs unbucketed step vs
       the per-bucket collective round-trip alone; asserts
       trainer.allreduce_overlap_fraction is published and > 0.
    3. **fp8 matmul** — parity (rel err <= 5e-2 at fp8_n x fp8_n),
       dispatch strictly follows the tuner table (fp8 dispatched iff
       the measured winner is fp8 — fp8.matmul_dispatch_total
       counter), and PADDLE_TPU_FP8_MATMUL beats the table both ways.
    4. **ZeRO-1 sharded optimizer state** — Adam, replicated vs
       shard_optimizer_state=True; asserts final params bit-identical
       and the analytic optimizer_state_bytes model shows per-device
       state reduced >= 0.8*dp (gauged at transpile).
    5. **quantized + bucketed composition** — both knobs on; asserts
       final-loss delta within the quant tolerance (EQuARX compression
       and bucket overlap stack).
    6. **MFU headline** — the unified transformer_mfu_est accounting
       vs XLA cost-analysis FLOPs on the reduced transformer (analytic
       / cost-analysis ratio within [1/3, 3]); tok/s + MFU deltas vs
       the BENCH_builder_r4_onchip capture recorded in the JSON.
    """
    import jax
    import jax.numpy as jnp

    from paddle_tpu import observe, tuning
    from paddle_tpu.ops.fp8_matmul import fp8_matmul, maybe_fp8_matmul
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import (ParallelStrategy,
                                                optimizer_state_bytes,
                                                transpile)
    from paddle_tpu.trainer import record_allreduce_overlap

    out = {'workload': 'trainspeed'}
    dp = max(1, min(int(dp), jax.device_count()))
    rng = np.random.RandomState(0)
    # dyadic feeds: every value is k/8, so dp partial sums are exact in
    # fp32 under ANY association — bit-identity asserts stay meaningful
    X = (rng.randint(-8, 8, (batch * dp, in_dim)) / 8.0) \
        .astype('float32')
    Y = (rng.randint(-8, 8, (batch * dp, 1)) / 8.0).astype('float32')

    def train_leg(bucket_mb=None, shard_opt=False, quant_on=False,
                  opt='sgd', n_steps=steps):
        fluid = _fresh()
        x = fluid.layers.data(name='x', shape=[in_dim], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        h = fluid.layers.fc(input=x, size=hidden, act='relu')
        h = fluid.layers.fc(input=h, size=hidden, act='relu')
        pred = fluid.layers.fc(input=h, size=1, act=None)
        cost = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        if opt == 'sgd':
            fluid.optimizer.SGD(learning_rate=0.125).minimize(cost)
        else:
            fluid.optimizer.Adam(learning_rate=0.125).minimize(cost)
        prog = fluid.default_main_program()
        prog.random_seed = 7
        if dp > 1:
            transpile(prog, make_mesh(dp=dp), ParallelStrategy(
                grad_bucket_mb=bucket_mb,
                shard_optimizer_state=True if shard_opt else None,
                quantized_allreduce=quant_on))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        losses, t0 = [], None
        for i in range(n_steps):
            got = exe.run(feed={'x': X, 'y': Y}, fetch_list=[cost])
            losses.append(float(np.asarray(got[0]).reshape(())))
            if i == 0:
                t0 = time.perf_counter()   # after the compiling step
        per_step = (time.perf_counter() - t0) / max(1, n_steps - 1)
        weights = {p.name: np.asarray(fluid.global_scope().find(p.name))
                   for p in prog.all_parameters()}
        return losses, weights, per_step, prog

    # ---- legs 1+2: bucketed bit-identity, then overlap -------------
    loss_f, w_f, t_fused, _ = train_leg()
    loss_b, w_b, t_buck, _ = train_leg(bucket_mb=0.001)
    g = observe.snapshot()['gauges']
    n_buckets = g.get('trainer.grad_bucket_count', 0)
    bit_identical = all(np.array_equal(w_f[k], w_b[k]) for k in w_f)
    if dp > 1:
        assert bit_identical, \
            'bucketed exact path must be bit-identical to unbucketed'
        assert n_buckets >= 2, \
            'bucket target 0.001MB formed %s buckets (< 2)' % n_buckets
    out['bucketing'] = {
        'dp': dp, 'steps': steps, 'n_buckets': int(n_buckets),
        'target_bytes': int(g.get('trainer.grad_bucket_target_bytes', 0)),
        'max_bucket_bytes': int(g.get('trainer.grad_bucket_max_bytes', 0)),
        'final_loss': round(loss_b[-1], 6),
        'bit_identical_to_unbucketed': bool(bit_identical),
    }

    overlap = {'dp': dp}
    if dp > 1:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        mesh = make_mesh(dp=dp)
        sizes = [max(dp, -(-int(w.size) // dp) * dp)
                 for w in w_f.values()]
        arrs = [jnp.ones((s,), jnp.float32) for s in sizes]

        @jax.jit
        def comm_fn(arrs):
            # the bucket collective boundary alone: one P('dp')/P()
            # constraint round trip per bucket-sized array
            outs = []
            for a in arrs:
                c = jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, P('dp')))
                outs.append(jax.lax.with_sharding_constraint(
                    c, NamedSharding(mesh, P())))
            return outs

        np.asarray(comm_fn(arrs)[0])                   # compile
        t0 = time.perf_counter()
        for _ in range(max(1, overlap_iters)):
            r = comm_fn(arrs)
        np.asarray(r[0])
        t_comm = (time.perf_counter() - t0) / max(1, overlap_iters)
        frac = record_allreduce_overlap(t_buck, t_fused, t_comm)
        assert frac is not None and frac > 0.0, \
            'overlap fraction %r (step %.5fs compute %.5fs comm %.5fs)' \
            % (frac, t_buck, t_fused, t_comm)
        g = observe.snapshot()['gauges']
        assert 'trainer.allreduce_overlap_fraction' in g, \
            'overlap gauge must be published'
        overlap.update(
            step_seconds=round(t_buck, 6),
            compute_seconds=round(t_fused, 6),
            comm_seconds=round(t_comm, 6),
            fraction=round(float(frac), 4))
    out['overlap'] = overlap

    # ---- leg 3: fp8 matmul parity + dispatch discipline ------------
    prng = np.random.RandomState(5)
    a = jnp.asarray(prng.randn(fp8_n, fp8_n).astype('float32'))
    b = jnp.asarray(prng.randn(fp8_n, fp8_n).astype('float32'))
    ref = np.asarray(jnp.matmul(a, b))
    rel = float(np.linalg.norm(np.asarray(fp8_matmul(a, b)) - ref)
                / np.linalg.norm(ref))
    assert rel <= 0.05, 'fp8 matmul rel err %.4f > 0.05' % rel

    import tempfile
    saved = {k: os.environ.get(k) for k in
             ('PADDLE_TPU_AUTOTUNE', 'PADDLE_TPU_TUNING_TABLE',
              'PADDLE_TPU_FP8_MATMUL')}
    tdir = tempfile.mkdtemp(prefix='trainspeed_tune_')

    def dispatch_count():
        return observe.snapshot()['counters'].get(
            'fp8.matmul_dispatch_total', 0)

    try:
        os.environ['PADDLE_TPU_AUTOTUNE'] = 'record'
        os.environ.pop('PADDLE_TPU_FP8_MATMUL', None)
        # fp8-winning table -> dispatched (and counted)
        os.environ['PADDLE_TPU_TUNING_TABLE'] = \
            os.path.join(tdir, 'fp8_wins.json')
        tuning.reset()
        tuning.set_timer(lambda op, key, v, t:
                         0.001 if v.get('impl') == 'fp8' else 0.010)
        c0 = dispatch_count()
        assert maybe_fp8_matmul(a, b) is not None, \
            'fp8 table winner must dispatch fp8'
        assert dispatch_count() == c0 + 1, 'dispatch counter must move'
        # explicit off gate beats the fp8-winning table
        os.environ['PADDLE_TPU_FP8_MATMUL'] = '0'
        assert maybe_fp8_matmul(a, b) is None, 'off gate beats table'
        # native-winning table -> NOT dispatched
        os.environ.pop('PADDLE_TPU_FP8_MATMUL', None)
        os.environ['PADDLE_TPU_TUNING_TABLE'] = \
            os.path.join(tdir, 'native_wins.json')
        tuning.reset()
        tuning.set_timer(lambda op, key, v, t:
                         0.001 if v.get('impl') == 'native' else 0.010)
        c0 = dispatch_count()
        assert maybe_fp8_matmul(a, b) is None, \
            'native table winner must NOT dispatch fp8'
        assert dispatch_count() == c0, \
            'no dispatch may be counted on the native path'
        # explicit on gate beats the native-winning table
        os.environ['PADDLE_TPU_FP8_MATMUL'] = '1'
        assert maybe_fp8_matmul(a, b) is not None, 'on gate beats table'
    finally:
        tuning.set_timer(None)
        tuning.reset()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    out['fp8'] = {'n': fp8_n, 'rel_err': round(rel, 5),
                  'dispatch_follows_table': True,
                  'env_gate_beats_table': True}

    # ---- leg 4: ZeRO-1 sharded optimizer state ---------------------
    loss_a, w_a, _, prog_a = train_leg(opt='adam')
    loss_z, w_z, _, prog_z = train_leg(opt='adam', shard_opt=True)
    z_bit = all(np.array_equal(w_a[k], w_z[k]) for k in w_a)
    mem_r = optimizer_state_bytes(prog_a)
    mem_z = optimizer_state_bytes(prog_z)
    if dp > 1:
        assert z_bit, 'ZeRO-1 params must be bit-identical to replicated'
        assert mem_z['reduction'] >= 0.8 * dp, \
            'optimizer state reduction %.2fx < 0.8*dp (dp=%d)' \
            % (mem_z['reduction'], dp)
        g = observe.snapshot()['gauges']
        assert 'trainer.optimizer_state_bytes_per_device' in g, \
            'ZeRO-1 memory gauge must be published at transpile'
    out['zero1'] = {
        'dp': dp, 'bit_identical_to_replicated': bool(z_bit),
        'state_bytes_total': mem_z['total'],
        'state_bytes_per_device_replicated': mem_r['per_device'],
        'state_bytes_per_device_sharded': mem_z['per_device'],
        'reduction_x': round(mem_z['reduction'], 3),
    }

    # ---- leg 5: quantized + bucketed composition -------------------
    loss_qb, _, _, _ = train_leg(bucket_mb=0.001, quant_on=True)
    delta = abs(loss_qb[-1] - loss_f[-1])
    tol = max(0.05, 0.25 * abs(loss_f[-1]))
    if dp > 1:
        assert delta <= tol, \
            'quantized+bucketed final loss %.4f vs exact %.4f (tol %.4f)' \
            % (loss_qb[-1], loss_f[-1], tol)
    out['quant_bucketed'] = {
        'final_loss_exact': round(loss_f[-1], 6),
        'final_loss_quant_bucketed': round(loss_qb[-1], 6),
        'loss_delta': round(delta, 6), 'tolerance': round(tol, 6),
    }

    # ---- leg 6: MFU — unified accounting + headline delta ----------
    saved_cost = os.environ.get('PADDLE_TPU_OBSERVE_COST')
    os.environ['PADDLE_TPU_OBSERVE_COST'] = '1'  # need executor.step_flops
    try:
        fluid = _fresh()
        from paddle_tpu.models import transformer as T
        avg_cost, _ = T.transformer_base(
            src_vocab_size=mfu_vocab, trg_vocab_size=mfu_vocab,
            src_seq_len=mfu_seq, trg_seq_len=mfu_seq,
            max_length=max(256, mfu_seq))
        fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        feed = _to_device(T.make_fake_batch(mfu_batch, mfu_seq, mfu_seq,
                                            mfu_vocab, mfu_vocab))
        got = exe.run(feed=feed, fetch_list=[avg_cost])  # single-step key
        np.asarray(got[0])
        xla_flops = observe.snapshot()['gauges'].get(
            'executor.step_flops', 0)
        dt = _time_multi(exe, feed, [avg_cost], mfu_iters)
    finally:
        if saved_cost is None:
            os.environ.pop('PADDLE_TPU_OBSERVE_COST', None)
        else:
            os.environ['PADDLE_TPU_OBSERVE_COST'] = saved_cost
    tok_s = mfu_batch * mfu_seq / dt
    analytic = _transformer_train_flops(mfu_batch, mfu_seq, mfu_seq,
                                        mfu_vocab)
    assert xla_flops, 'executor.step_flops gauge missing — the unified ' \
        'MFU path needs the XLA cost analysis'
    ratio = analytic / xla_flops
    # analytic counts matmul FLOPs only (x3 bwd); XLA counts the whole
    # program — agreement within 3x is the unification contract
    assert 1.0 / 3.0 <= ratio <= 3.0, \
        'analytic %.3e vs cost-analysis %.3e FLOPs (ratio %.3f)' \
        % (analytic, xla_flops, ratio)
    mfu = transformer_mfu_est(tok_s, mfu_batch, mfu_seq, mfu_vocab)
    mfu_leg = {
        'batch': mfu_batch, 'seq': mfu_seq, 'vocab': mfu_vocab,
        'tok_per_sec': round(tok_s, 1), 'mfu_est': round(mfu, 6),
        'analytic_flops_per_step': analytic,
        'xla_cost_analysis_flops': xla_flops,
        'analytic_vs_xla_ratio': round(ratio, 3),
    }
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               'BENCH_builder_r4_onchip.json')) as f:
            cap = json.load(f)
        base_tok = float(cap['detail']['transformer_tok_per_sec'])
        base_mfu = transformer_mfu_est(base_tok)  # headline shapes
        mfu_leg['baseline'] = {
            'file': 'BENCH_builder_r4_onchip.json',
            'transformer_tok_per_sec': base_tok,
            'mfu_est': round(base_mfu, 4),
            'tok_per_sec_delta': round(tok_s - base_tok, 1),
            'tok_per_sec_ratio': round(tok_s / base_tok, 6),
            'mfu_delta': round(mfu - base_mfu, 6),
            'note': 'measured leg runs the reduced shape on this '
                    'backend; the baseline is the on-chip headline '
                    'shape — deltas recorded, not asserted',
        }
    except Exception as e:
        mfu_leg['baseline'] = {'error': '%s: %s' % (type(e).__name__, e)}
    out['mfu'] = mfu_leg
    return out


def bench_autoscale(in_dim=8, max_batch=8, max_queue_depth=12,
                    compute_delay_ms=10.0, latency_budget_s=0.05,
                    availability=0.95, window_s=1.5,
                    flash_duration=4.0, flash_steady_qps=30.0,
                    flash_spike_qps=500.0, flash_spike_at=1.2,
                    crash_duration=4.0, crash_qps=40.0, crash_kills=4,
                    crash_interval_s=0.45, crash_first_kill_at=0.6,
                    trough_duration=4.0, trough_high_qps=40.0,
                    trough_low_qps=4.0, trough_drop_at=1.0,
                    retry_budget=0.1, retry_budget_burst=20.0,
                    trace_sample=0.05):
    """Self-healing autoscaling chaos suite (ISSUE 11): three scenarios
    through one FleetController + hedging Router, each measured (the
    test asserts):

    1. **flash crowd** — offered load jumps ~15x; the controller must
       scale out (AOT-warm spawns) before the error budget burns
       through: burn spikes >1x then recovers <1x within the run,
       with zero accepted-request loss.
    2. **crash loop** — one replica slot is killed repeatedly
       (fault.inject.crash_loop); the circuit breaker must quarantine
       the flapping lineage (flight event + counter) and goodput must
       recover on the survivors.
    3. **diurnal trough** — load drops ~10x; the controller must scale
       in by drain-then-shutdown with zero accepted-request loss and
       zero errors.

    Hedged requests run throughout: the returned ``hedge`` ledger
    proves retry traffic (hedges + failovers) stayed inside the token
    budget ``retry_budget x accepted + burst`` and that no hedge ever
    produced a result differing from its primary
    (``router.hedge_mismatch_total == 0``). Periodic JSONL snapshots
    (observe.flush) make the scale timeline reconstructable by
    ``tools/metrics_report.py --fleet``."""
    import threading

    from paddle_tpu import observe
    from paddle_tpu.fault import inject
    from paddle_tpu.observe.slo import Objective, SloTracker
    from paddle_tpu.serving import (FleetController,
                                    NoReplicaAvailableError, Router,
                                    ServingEngine)
    from paddle_tpu.serving.loadgen import (Stats, flash_crowd,
                                            open_loop, percentiles)

    model_dir = _save_chaos_model(in_dim)
    from paddle_tpu.inference import create_predictor

    delay_s = float(compute_delay_ms) / 1000.0
    aot_dir = os.path.join(os.path.dirname(model_dir), 'aot_cache')

    def make_engine(name):
        """The ReplicaFactory: a fresh predictor over the shared AOT
        executable cache, so every spawn after the first warm-starts
        from serialized executables instead of compiling."""
        pred = _ChaosPredictor(create_predictor(model_dir), delay_s)
        return ServingEngine(pred, max_batch_size=max_batch,
                             batch_timeout_ms=1.0,
                             max_queue_depth=max_queue_depth,
                             name=name)

    def counter_sum(snap, prefix):
        return sum(v for k, v in snap['counters'].items()
                   if k.startswith(prefix))

    def run_scenario(tag, qps_spec, duration, n_start, ctl_kw,
                     chaos=None, deadline_s=None):
        """One scenario: fresh fleet + controller, open-loop load,
        sampler thread (burn/goodput/census timeline + periodic JSONL
        snapshots), optional chaos thread. Returns the measured dict
        (counter values are per-scenario deltas)."""
        snap0 = observe.snapshot()
        engines = []
        t_w0 = time.perf_counter()
        for i in range(n_start):
            eng = make_engine('%s%d' % (tag, i))
            eng.warmup()
            eng.start()
            engines.append(eng)
        warmup_s = time.perf_counter() - t_w0
        tracker = SloTracker([Objective(tag, latency_budget_s,
                                        availability_target=availability,
                                        window_s=window_s)])
        router = Router(engines, slo=tracker, route=tag, retries=3,
                        hedge=True, retry_budget=retry_budget,
                        retry_budget_burst=retry_budget_burst)
        ctl = FleetController(router, make_engine, slo=tracker,
                              route=tag, name_prefix='%s-auto' % tag,
                              **ctl_kw)
        ctl.start()

        stats = Stats()
        submitted = [0]
        no_replica = [0]

        def submit_request(rng):
            rows = int(rng.randint(1, max(2, max_batch // 2)))
            feed = {'x': rng.rand(rows, in_dim).astype('float32')}
            try:
                fut = router.submit(feed,
                                    session=int(rng.randint(0, 64)),
                                    deadline_s=deadline_s)
            except NoReplicaAvailableError:
                no_replica[0] += 1
                return None
            submitted[0] += 1
            return fut, rows

        burn_timeline, census_timeline = [], []
        goodput_timeline = []
        t0 = time.perf_counter()
        stop = threading.Event()

        def sampler():
            last_flush = 0.0
            while not stop.wait(0.05):
                now = time.perf_counter()
                t = round(now - t0, 3)
                burn_timeline.append(
                    (t, tracker.burn_rate(tag, now)))
                goodput_timeline.append(
                    (t, tracker.goodput(tag, now)))
                census_timeline.append((t, ctl.census()))
                if now - last_flush >= 0.25:
                    last_flush = now
                    observe.flush(kind='snapshot')

        threads = [threading.Thread(target=sampler, daemon=True)]
        chaos_result = {}
        if chaos is not None:
            threads.append(threading.Thread(
                target=lambda: chaos_result.update(chaos(ctl, t0)),
                daemon=True))
        for t in threads:
            t.start()
        open_loop(submit_request, stats, t0 + duration, qps_spec)
        ctl.close()                    # stop ticking before teardown
        for name, rep in router.replicas():
            rep.shutdown(drain=True)
        t_end = time.perf_counter() + 15.0
        while stats.ok + stats.errors < submitted[0] and \
                time.perf_counter() < t_end:
            time.sleep(0.01)
        stop.set()
        wall = time.perf_counter() - t0
        for t in threads:
            t.join(timeout=10)
        ctl.close(shutdown_replicas=True)
        router.close()
        tracker.publish()
        observe.flush(kind='snapshot')

        snap1 = observe.snapshot()
        delta = lambda prefix: (counter_sum(snap1, prefix)  # noqa: E731
                                - counter_sum(snap0, prefix))
        accepted = submitted[0]
        completed = stats.ok + stats.errors
        # end-of-LOAD burn (samples past `duration` are teardown decay
        # and would flatter the recovery claim)
        tail = [b for t, b in burn_timeline
                if 0.85 * duration <= t <= duration]
        peak_census = {}
        for _, c in census_timeline:
            for k, v in c.items():
                peak_census[k] = max(peak_census.get(k, 0), v)
        return dict({
            'scenario': tag,
            'duration_s': round(wall, 3),
            'accepted': accepted,
            'completed': completed,
            'lost': accepted - completed,
            'requests_ok': stats.ok,
            'requests_rejected': stats.rejected,
            'requests_errored': stats.errors,
            'no_replica': no_replica[0],
            'latency_ms': percentiles(stats.latencies),
            'warmup_s': round(warmup_s, 3),
            'burn_peak': round(max([b for _, b in burn_timeline]
                                   or [0.0]), 4),
            'burn_end': round(min(tail) if tail else 0.0, 4),
            'burn_timeline': burn_timeline,
            'goodput_end_rps': round(
                sum(g for _, g in goodput_timeline[-6:])
                / max(1, len(goodput_timeline[-6:])), 2),
            'census_timeline': census_timeline[::4],
            'census_peak': peak_census,
            'scale_outs': delta('controller.scale_out_total'),
            'scale_ins': delta('controller.scale_in_total'),
            'heals': delta('controller.heals_total'),
            'deaths': delta('controller.deaths_total'),
            'quarantines': delta('controller.quarantines_total'),
            'spawn_failures':
                delta('controller.spawn_failures_total'),
            'drain_timeouts': delta('controller.drain_timeouts_total'),
            'dispatches': delta('router.dispatch_total'),
            'hedges': delta('router.hedge_total'),
            'hedge_mismatches': delta('router.hedge_mismatch_total'),
            'failovers': delta('router.failover_total'),
        }, **chaos_result)

    prev = {k: os.environ.get(k) for k in
            ('PADDLE_TPU_TRACE_SAMPLE', 'PADDLE_TPU_AOT_CACHE',
             'PADDLE_TPU_AOT_CACHE_DIR')}
    os.environ['PADDLE_TPU_TRACE_SAMPLE'] = str(trace_sample)
    # spawns ride the AOT executable cache: the first warmup populates
    # it, every later spawn (the scale-up path) deserializes
    os.environ['PADDLE_TPU_AOT_CACHE'] = '1'
    os.environ['PADDLE_TPU_AOT_CACHE_DIR'] = aot_dir
    try:
        # 1 — flash crowd: must scale out before the budget burns away
        flash = run_scenario(
            'flash',
            flash_crowd(flash_steady_qps, flash_spike_qps,
                        flash_spike_at,
                        flash_duration - flash_spike_at),
            flash_duration, n_start=2,
            ctl_kw=dict(min_replicas=2, max_replicas=6,
                        interval_s=0.1, burn_high=1.0, queue_high=3.0,
                        scale_out_cooldown_s=0.35, trough_s=1e9,
                        scale_step=2),
            deadline_s=latency_budget_s)

        # 2 — crash loop: repeated kills of ONE slot must quarantine
        def crash_chaos(ctl, t0):
            wait = crash_first_kill_at - (time.perf_counter() - t0)
            if wait > 0:
                time.sleep(wait)
            # the lineage-aware resolver: every kill lands on whatever
            # replacement the controller spawned for slot 'crash2'
            kills = inject.crash_loop(
                lambda: ctl.current('crash2'),
                kills=crash_kills, interval_s=crash_interval_s)
            return {'kills_performed': kills}

        crash = run_scenario(
            'crash', crash_qps, crash_duration, n_start=3,
            ctl_kw=dict(min_replicas=2, max_replicas=4,
                        interval_s=0.1, backoff_base_s=0.05,
                        backoff_max_s=0.4, crash_loop_threshold=2,
                        crash_window_s=10.0, quarantine_s=60.0,
                        trough_s=1e9, scale_out_cooldown_s=1e9),
            chaos=crash_chaos)

        # 3 — diurnal trough: scale-in drains with zero request loss
        trough = run_scenario(
            'trough',
            [(0.0, trough_high_qps), (trough_drop_at, trough_low_qps)],
            trough_duration, n_start=4,
            ctl_kw=dict(min_replicas=2, max_replicas=4,
                        interval_s=0.1, burn_low=0.5, queue_low=1.5,
                        trough_s=0.6, scale_in_cooldown_s=0.5,
                        scale_out_cooldown_s=1e9, queue_high=1e9,
                        burn_high=1e9))
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # the hedging contract across all three scenarios: retry traffic
    # (every dispatch past each request's primary) never exceeded the
    # token budget, and no hedge disagreed with its primary
    accepted = sum(s['accepted'] for s in (flash, crash, trough))
    retry_dispatches = sum(s['dispatches'] - s['accepted']
                           for s in (flash, crash, trough))
    bound = retry_budget * accepted + 3 * retry_budget_burst
    return {
        'workload': 'autoscale',
        'flash_crowd': flash,
        'crash_loop': crash,
        'trough': trough,
        'hedge': {
            'accepted': accepted,
            'hedges': sum(s['hedges'] for s in (flash, crash, trough)),
            'failovers': sum(s['failovers']
                             for s in (flash, crash, trough)),
            'retry_dispatches': retry_dispatches,
            'retry_budget': retry_budget,
            'retry_budget_burst': retry_budget_burst,
            'bound': round(bound, 2),
            'within_budget': retry_dispatches <= bound,
            'mismatches': sum(s['hedge_mismatches']
                              for s in (flash, crash, trough)),
        },
    }


def bench_crosshost(in_dim=8, max_batch=4, max_queue_depth=16,
                    compute_delay_ms=15.0, latency_budget_s=0.2,
                    availability=0.9, window_s=1.5,
                    kill_duration=8.0, kill_qps=18.0, kill_at=2.5,
                    hung_duration=10.0, hung_qps=10.0, stall_at=2.0,
                    crash_duration=12.0, crash_qps=8.0, crash_kills=3,
                    crash_interval_s=2.5, crash_first_kill_at=1.0,
                    heartbeat_timeout_s=0.6, replace_window_s=45.0,
                    spawn_timeout_s=180.0, identity_requests=12,
                    trace_sample=0.05):
    """Cross-host fleet chaos (ISSUE 16): the replica-kill / hung-
    worker / crash-loop scenarios with the fleet split across REAL
    worker processes (serving.rpc.ProcessReplicaFactory spawning
    tools/replica_worker.py), kills delivered as real SIGKILL to live
    PIDs (fault.inject.kill_process). Asserts the tentpole contract
    directly:

    1. **replica kill** — SIGKILL one worker mid-load: zero
       accepted-request loss (router failover resubmits in-flight
       work typed as RemoteReplicaError), only typed error classes
       observed, the victim's /readyz flip seen over plain HTTP, and
       the controller heals the slot (a fresh process).
    2. **hung worker** — SIGSTOP (alive but wedged): the /readyz
       heartbeat timeout declares it dead, the corpse is SIGKILLed +
       reaped, and a replacement is UP within ``replace_window_s``.
    3. **crash loop** — repeated kill_process on one lineage's
       replacements trips the quarantine breaker.
    4. **bit identity** — the same deterministic request stream
       through a subprocess replica and an in-process engine yields
       byte-identical outputs.

    Per-worker metrics JSONLs land beside the parent's sink;
    ``tools/metrics_report.py --fleet <dir>`` renders the merged run
    (per-replica census from child-emitted worker.* gauges)."""
    import signal as _signal
    import threading

    from paddle_tpu import observe
    from paddle_tpu.fault import inject
    from paddle_tpu.inference import create_predictor
    from paddle_tpu.observe.slo import Objective, SloTracker
    from paddle_tpu.serving import (FleetController,
                                    NoReplicaAvailableError,
                                    ProcessReplicaFactory, Router,
                                    ServingEngine)
    from paddle_tpu.serving.loadgen import (Stats, open_loop,
                                            percentiles)

    model_dir = _save_chaos_model(in_dim)
    aot_dir = os.path.join(os.path.dirname(model_dir), 'aot_cache')
    delay_s = float(compute_delay_ms) / 1000.0

    # the typed vocabulary: every error a chaos run is ALLOWED to
    # surface to a client (anything else is a bug, asserted below)
    typed_errors = {'RemoteReplicaError', 'EngineClosedError',
                    'QueueFullError', 'SLOShedError',
                    'NoReplicaAvailableError', 'TimeoutError'}

    worker_config = {
        'kind': 'serving', 'model_dir': model_dir, 'backend': 'cpu',
        'compute_delay_ms': compute_delay_ms,
        'engine': {'max_batch_size': max_batch,
                   'batch_timeout_ms': 1.0,
                   'max_queue_depth': max_queue_depth}}

    def http_readyz(url, timeout=1.0):
        """GET /readyz over plain HTTP: status code, or None when the
        TCP layer already says dead — the flip a real balancer sees."""
        import http.client
        hostport = url.rstrip('/').split('://', 1)[-1]
        host, _, port = hostport.rpartition(':')
        try:
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=timeout)
            conn.request('GET', '/readyz')
            resp = conn.getresponse()
            resp.read()
            conn.close()
            return resp.status
        except Exception:
            return None

    def counter_sum(snap, prefix):
        return sum(v for k, v in snap['counters'].items()
                   if k.startswith(prefix))

    def run_scenario(tag, qps, duration, n_start, ctl_kw, chaos=None):
        """One scenario over a fresh SUBPROCESS fleet. Same shape as
        bench_autoscale's runner; every replica here is a PID."""
        snap0 = observe.snapshot()
        factory = ProcessReplicaFactory(
            worker_config, spawn_timeout_s=spawn_timeout_s,
            heartbeat_timeout_s=heartbeat_timeout_s,
            admission_timeout_s=3.0)
        t_w0 = time.perf_counter()
        replicas = [factory.create('%s%d' % (tag, i))
                    for i in range(n_start)]
        warmup_s = time.perf_counter() - t_w0
        tracker = SloTracker([Objective(tag, latency_budget_s,
                                        availability_target=availability,
                                        window_s=window_s)])
        router = Router(replicas, slo=tracker, route=tag, retries=3,
                        hedge=False)
        ctl = FleetController(router, factory, slo=tracker, route=tag,
                              name_prefix='%s-x' % tag, **ctl_kw)
        ctl.start()

        stats = Stats()
        submitted = [0]
        no_replica = [0]
        error_types = set()

        def submit_request(rng):
            rows = int(rng.randint(1, max_batch + 1))
            feed = {'x': rng.rand(rows, in_dim).astype('float32')}
            try:
                fut = router.submit(feed,
                                    session=int(rng.randint(0, 64)))
            except NoReplicaAvailableError:
                no_replica[0] += 1
                return None
            submitted[0] += 1

            def _type_cb(f):
                exc = f.exception()
                if exc is not None:
                    error_types.add(type(exc).__name__)
            fut.add_done_callback(_type_cb)
            return fut, rows

        goodput_timeline, census_timeline = [], []
        t0 = time.perf_counter()
        stop = threading.Event()

        def sampler():
            last_flush = 0.0
            while not stop.wait(0.05):
                now = time.perf_counter()
                t = round(now - t0, 3)
                goodput_timeline.append((t, tracker.goodput(tag, now)))
                census_timeline.append((t, ctl.census()))
                if now - last_flush >= 0.25:
                    last_flush = now
                    observe.flush(kind='snapshot')

        threads = [threading.Thread(target=sampler, daemon=True)]
        chaos_result = {}
        if chaos is not None:
            threads.append(threading.Thread(
                target=lambda: chaos_result.update(
                    chaos(ctl, router, factory, t0)), daemon=True))
        for t in threads:
            t.start()
        open_loop(submit_request, stats, t0 + duration, qps)
        ctl.close()                    # stop ticking before teardown
        for _name, rep in router.replicas():
            rep.shutdown(drain=True)
        t_end = time.perf_counter() + 20.0
        while stats.ok + stats.errors < submitted[0] and \
                time.perf_counter() < t_end:
            time.sleep(0.01)
        stop.set()
        wall = time.perf_counter() - t0
        for t in threads:
            t.join(timeout=15)
        ctl.close(shutdown_replicas=True)
        router.close()
        factory.close()                # no PID outlives the scenario
        tracker.publish()
        observe.flush(kind='snapshot')

        snap1 = observe.snapshot()
        delta = lambda prefix: (counter_sum(snap1, prefix)  # noqa: E731
                                - counter_sum(snap0, prefix))
        accepted = submitted[0]
        completed = stats.ok + stats.errors
        return dict({
            'scenario': tag,
            'duration_s': round(wall, 3),
            'spawn_s': round(warmup_s, 3),
            'accepted': accepted,
            'completed': completed,
            'lost': accepted - completed,
            'requests_ok': stats.ok,
            'requests_rejected': stats.rejected,
            'requests_errored': stats.errors,
            'no_replica': no_replica[0],
            'error_types': sorted(error_types),
            'untyped_errors': sorted(error_types - typed_errors),
            'latency_ms': percentiles(stats.latencies),
            'goodput_end_rps': round(
                sum(g for _, g in goodput_timeline[-6:])
                / max(1, len(goodput_timeline[-6:])), 2),
            'census_timeline': census_timeline[::6],
            'heals': delta('controller.heals_total'),
            'deaths': delta('controller.deaths_total'),
            'quarantines': delta('controller.quarantines_total'),
            'spawn_failures': delta('controller.spawn_failures_total'),
            'failovers': delta('router.failover_total'),
            'process_kills': delta('fault.process_kills_total'),
        }, **chaos_result)

    def wait_replaced(ctl, base, victim, t_from, budget):
        """Block until lineage ``base`` holds a DIFFERENT live replica
        than ``victim`` (the controller declared the death and spawned
        a replacement process); seconds-to-heal or None on timeout."""
        deadline = t_from + budget
        while time.perf_counter() < deadline:
            cur = ctl.current(base)
            if cur is not None and cur is not victim:
                return round(time.perf_counter() - t_from, 3)
            time.sleep(0.05)
        return None

    def kill_chaos(ctl, router, factory, t0):
        wait = kill_at - (time.perf_counter() - t0)
        if wait > 0:
            time.sleep(wait)
        victim = ctl.current('kill0')
        if victim is None:           # slot already churned: any UP one
            live = [r for _n, r in router.replicas() if r.ready()]
            victim = live[0] if live else None
        if victim is None:
            return {'killed_pid': None}
        readyz_before = http_readyz(victim.url)
        pid = inject.kill_process(victim)
        t_kill = time.perf_counter()
        readyz_after = None
        for _ in range(200):         # the HTTP-visible flip
            status = http_readyz(victim.url, timeout=0.25)
            if status != 200:
                readyz_after = status
                break
            time.sleep(0.02)
        healed_in = wait_replaced(ctl, 'kill0', victim, t_kill,
                                  replace_window_s)
        return {'killed_pid': pid,
                'readyz_before': readyz_before,
                'readyz_after': readyz_after,
                'readyz_flipped': (readyz_before == 200
                                   and readyz_after != 200),
                'healed_in_s': healed_in}

    def hung_chaos(ctl, router, factory, t0):
        wait = stall_at - (time.perf_counter() - t0)
        if wait > 0:
            time.sleep(wait)
        victim = ctl.current('hung0')
        if victim is None:
            return {'stalled_pid': None}
        pid = inject.kill_process(victim, sig=_signal.SIGSTOP)
        t_stop = time.perf_counter()
        # the worker is ALIVE (kernel still completes its TCP
        # handshakes) but answers nothing: only the heartbeat timeout
        # can declare it dead
        replaced_in = wait_replaced(ctl, 'hung0', victim, t_stop,
                                    replace_window_s)
        # defence in depth: the controller's reap path SIGKILLs the
        # stopped corpse; if the window elapsed without that, unwedge
        # so no stopped PID outlives the bench
        try:
            os.kill(pid, _signal.SIGKILL)
        except (OSError, TypeError):
            pass
        return {'stalled_pid': pid, 'replaced_in_s': replaced_in,
                'declared_dead_by_heartbeat': replaced_in is not None}

    def crash_chaos(ctl, router, factory, t0):
        wait = crash_first_kill_at - (time.perf_counter() - t0)
        if wait > 0:
            time.sleep(wait)
        kills = 0
        for i in range(crash_kills):
            if i:
                time.sleep(crash_interval_s)
            # lineage-aware: every kill lands on whatever replacement
            # the controller just spawned for slot 'crash1'
            pid = inject.kill_process(lambda: ctl.current('crash1'))
            if pid is not None:
                kills += 1
        # the breaker engaging is a census fact, not a counter: the
        # flapping lineage must land in QUARANTINED
        engaged = False
        deadline = time.perf_counter() + replace_window_s
        while time.perf_counter() < deadline:
            if ctl.census().get('QUARANTINED', 0) >= 1:
                engaged = True
                break
            time.sleep(0.05)
        return {'kills_performed': kills,
                'quarantine_engaged': engaged}

    def identity_leg():
        """Same deterministic request stream through a subprocess
        replica and an in-process engine: outputs must be
        byte-identical."""
        factory = ProcessReplicaFactory(
            worker_config, spawn_timeout_s=spawn_timeout_s,
            heartbeat_timeout_s=heartbeat_timeout_s)
        remote = factory.create('ident0')
        local = ServingEngine(
            _ChaosPredictor(create_predictor(model_dir), delay_s),
            max_batch_size=max_batch, batch_timeout_ms=1.0,
            max_queue_depth=max_queue_depth, name='ident-local')
        local.warmup()
        local.start()
        rng = np.random.RandomState(1234)
        mismatches = 0
        try:
            for i in range(identity_requests):
                rows = (i % max_batch) + 1
                feed = {'x': rng.rand(rows, in_dim).astype('float32')}
                r_out = remote.submit(dict(feed)).result(30)
                l_out = local.submit(dict(feed)).result(30)
                for a, b in zip(r_out, l_out):
                    a, b = np.asarray(a), np.asarray(b)
                    if a.dtype != b.dtype or a.shape != b.shape or \
                            a.tobytes() != b.tobytes():
                        mismatches += 1
        finally:
            local.shutdown(drain=True)
            remote.shutdown(drain=True)
            factory.close()
        return {'requests': identity_requests,
                'mismatches': mismatches,
                'bit_identical': mismatches == 0}

    prev = {k: os.environ.get(k) for k in
            ('PADDLE_TPU_TRACE_SAMPLE', 'PADDLE_TPU_AOT_CACHE',
             'PADDLE_TPU_AOT_CACHE_DIR')}
    os.environ['PADDLE_TPU_TRACE_SAMPLE'] = str(trace_sample)
    # the AOT executable cache dir is INHERITED by every worker spawn:
    # the first worker's warmup populates it, every later spawn (the
    # heal path under chaos) warm-starts from serialized executables
    os.environ['PADDLE_TPU_AOT_CACHE'] = '1'
    os.environ['PADDLE_TPU_AOT_CACHE_DIR'] = aot_dir
    try:
        kill = run_scenario(
            'kill', kill_qps, kill_duration, n_start=2,
            ctl_kw=dict(min_replicas=2, max_replicas=3,
                        interval_s=0.1, backoff_base_s=0.05,
                        backoff_max_s=0.4, trough_s=1e9,
                        scale_out_cooldown_s=1e9, queue_high=1e9,
                        burn_high=1e9),
            chaos=kill_chaos)
        hung = run_scenario(
            'hung', hung_qps, hung_duration, n_start=2,
            ctl_kw=dict(min_replicas=2, max_replicas=3,
                        interval_s=0.1, backoff_base_s=0.05,
                        backoff_max_s=0.4, trough_s=1e9,
                        scale_out_cooldown_s=1e9, queue_high=1e9,
                        burn_high=1e9),
            chaos=hung_chaos)
        crash = run_scenario(
            'crash', crash_qps, crash_duration, n_start=2,
            ctl_kw=dict(min_replicas=1, max_replicas=3,
                        interval_s=0.1, backoff_base_s=0.05,
                        backoff_max_s=0.3, crash_loop_threshold=2,
                        crash_window_s=60.0, quarantine_s=120.0,
                        trough_s=1e9, scale_out_cooldown_s=1e9,
                        queue_high=1e9, burn_high=1e9),
            chaos=crash_chaos)
        identity = identity_leg()
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    result = {
        'workload': 'crosshost',
        'replica_kill': kill,
        'hung_worker': hung,
        'crash_loop': crash,
        'bit_identity': identity,
    }
    # the tentpole contract, asserted HERE (ISSUE 16 acceptance): a
    # crosshost bench run that returns is a crosshost bench run that
    # held the line
    assert kill['lost'] == 0, 'accepted requests lost: %r' % kill
    assert not kill['untyped_errors'], \
        'untyped errors surfaced: %s' % kill['untyped_errors']
    assert kill.get('killed_pid'), 'chaos never killed a live PID'
    assert kill.get('readyz_flipped'), \
        'readyz flip not observed over HTTP: %r' % kill
    assert kill.get('healed_in_s') is not None, \
        'controller never healed the killed slot: %r' % kill
    assert hung.get('declared_dead_by_heartbeat'), \
        'hung worker not declared dead within %.0fs: %r' \
        % (replace_window_s, hung)
    assert hung['lost'] == 0 and not hung['untyped_errors'], \
        'hung-worker scenario lost/mistyped requests: %r' % hung
    assert crash.get('quarantine_engaged'), \
        'crash loop never tripped quarantine: %r' % crash
    assert identity['bit_identical'], \
        'subprocess vs in-process results diverged: %r' % identity
    return result


def bench_multitenant(in_dim=8, max_batch=8, max_queue_depth=16,
                      compute_delay_ms=8.0, interactive_qps=25.0,
                      batch_quota_rps=10.0, flood_factor=10.0,
                      mix_duration=3.0, quota_rps=8.0, quota_qps=40.0,
                      quota_duration=2.0, inv_batch_new=40,
                      inv_inter_new=8, latency_budget_s=0.002,
                      window_s=1.2, tick_s=0.05, train_batches=10,
                      train_split=3):
    """Multi-tenant fleet chaos (ISSUE 18): four scenarios through the
    serving.tenancy policy layer, the acceptance contract asserted
    inline (a run that returns is a run that held the line):

    1. **noisy neighbor** — an interactive tenant's goodput is first
       measured solo, then again while a batch tenant floods
       ``flood_factor``x its request quota: the token bucket sheds the
       flood at admission, so interactive goodput stays within 10% of
       the solo baseline.
    2. **quota exhaustion** — a tenant offered well past its quota:
       every shed is the typed ``QuotaExceededError`` (never a bare
       queue-full), and the in-quota traffic that WAS admitted loses
       nothing.
    3. **priority inversion** — a decode engine whose KV pool the
       batch class has saturated receives interactive arrivals: pool
       exhaustion preempts only batch sequences (lowest class first),
       interactive preemptions stay zero while every interactive
       request completes.
    4. **co-location** — a background fine-tuning Trainer shares the
       host with serving; SLO-violating traffic drives the burn rate
       past 1 and ``colocation_yield`` yields the trainer within one
       FleetController tick (``tenant_yield`` flight event +
       ``tenant.trainer_yields_total``), calm resumes it, and the
       final params are bit-identical to an uninterrupted run at the
       same step count.

    ``tenant.admitted/shed/preempted/evicted_pages`` land in the
    metrics JSONL; ``tools/metrics_report.py --tenants`` renders the
    per-tenant isolation panel."""
    import threading

    from paddle_tpu import observe
    from paddle_tpu.observe.slo import Objective, SloTracker
    from paddle_tpu.serving import (FleetController, QueueFullError,
                                    NoReplicaAvailableError,
                                    QuotaExceededError, Router,
                                    ServingEngine, TenantRegistry,
                                    colocation_yield,
                                    slo_burn_pressure)
    from paddle_tpu.serving.loadgen import (Stats, open_loop,
                                            percentiles)

    model_dir = _save_chaos_model(in_dim)
    from paddle_tpu.inference import create_predictor

    delay_s = float(compute_delay_ms) / 1000.0

    def make_engine(name):
        pred = _ChaosPredictor(create_predictor(model_dir), delay_s)
        return ServingEngine(pred, max_batch_size=max_batch,
                             batch_timeout_ms=1.0,
                             max_queue_depth=max_queue_depth,
                             name=name)

    def counter_sel(snap, prefix, substr=''):
        return sum(v for k, v in snap['counters'].items()
                   if k.startswith(prefix) and substr in k)

    # ------------------------------------------------- mix harness
    def run_mix(tag, registry, traffic, duration, n_engines=2):
        """Open-loop pacers, one per tenant (``traffic`` is
        ``[(tenant, qps, sessions)]``), through one quota-equipped
        Router. Returns per-tenant admission/goodput ledgers plus the
        tenant.* counter deltas for the window."""
        snap0 = observe.snapshot()
        engines = []
        for i in range(n_engines):
            eng = make_engine('%s%d' % (tag, i))
            eng.warmup()
            eng.start()
            engines.append(eng)
        router = Router(engines, route=tag, tenants=registry)
        t0 = time.perf_counter()
        per, threads = {}, []
        for seed, (name, qps, sessions) in enumerate(traffic):
            led = {'stats': Stats(t0), 'submitted': [0],
                   'typed': [0], 'untyped': [0]}

            def submit_request(rng, name=name, sessions=sessions,
                               led=led):
                feed = {'x': rng.rand(1, in_dim).astype('float32')}
                session = '%s/s%d' % (name,
                                      int(rng.randint(sessions)))
                try:
                    fut = router.submit(feed, session=session)
                except QuotaExceededError:
                    led['typed'][0] += 1
                    return None
                except (QueueFullError, NoReplicaAvailableError):
                    led['untyped'][0] += 1
                    return None
                led['submitted'][0] += 1
                return fut, 1

            per[name] = led
            threads.append(threading.Thread(
                target=open_loop,
                args=(submit_request, led['stats'], t0 + duration,
                      qps),
                kwargs=dict(seed=101 + seed), daemon=True))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for eng in engines:
            eng.shutdown(drain=True)
        accepted = sum(led['submitted'][0] for led in per.values())
        t_end = time.perf_counter() + 15.0
        while sum(led['stats'].ok + led['stats'].errors
                  for led in per.values()) < accepted and \
                time.perf_counter() < t_end:
            time.sleep(0.01)
        router.close()
        snap1 = observe.snapshot()
        out = {'scenario': tag, 'duration_s': duration, 'tenants': {}}
        for name, led in per.items():
            s = led['stats']
            out['tenants'][name] = {
                'offered': led['submitted'][0] + s.rejected,
                'admitted': led['submitted'][0],
                'ok': s.ok,
                'errors': s.errors,
                'lost': led['submitted'][0] - (s.ok + s.errors),
                'quota_sheds': led['typed'][0],
                'untyped_rejects': led['untyped'][0],
                'goodput_rps': round(s.ok / duration, 2),
                'latency_ms': percentiles(s.latencies),
                'shed_counter': counter_sel(
                    snap1, 'tenant.shed', 'tenant=%s' % name)
                - counter_sel(snap0, 'tenant.shed',
                              'tenant=%s' % name),
            }
        return out

    # 1 — noisy neighbor: batch flood vs interactive goodput
    def mk_registry():
        reg = TenantRegistry()
        reg.add('fg', priority='interactive')
        reg.add('bg', priority='batch', request_rate=batch_quota_rps)
        return reg

    solo = run_mix('nnsolo', mk_registry(),
                   [('fg', interactive_qps, 8)], mix_duration)
    flood_qps = flood_factor * batch_quota_rps
    mixed = run_mix('nnmix', mk_registry(),
                    [('fg', interactive_qps, 8),
                     ('bg', flood_qps, 8)], mix_duration)
    solo_fg = solo['tenants']['fg']
    mix_fg = mixed['tenants']['fg']
    mix_bg = mixed['tenants']['bg']
    isolation = mix_fg['ok'] / float(max(1, solo_fg['ok']))
    noisy = {'solo': solo, 'mixed': mixed,
             'flood_qps': flood_qps,
             'isolation_ratio': round(isolation, 4)}

    # 2 — quota exhaustion: typed sheds, zero loss for admitted work
    reg = TenantRegistry()
    reg.add('acme', priority='standard', request_rate=quota_rps)
    quota = run_mix('quota', reg, [('acme', quota_qps, 4)],
                    quota_duration, n_engines=1)
    acme = quota['tenants']['acme']
    quota['quota_rps'] = quota_rps
    quota['offered_qps'] = quota_qps

    # 3 — priority inversion: batch saturates the KV pool, then
    # interactive arrives; only batch may be preempted
    def run_inversion():
        from paddle_tpu.serving.decode import DecodeEngine, LMSpec
        spec = LMSpec(vocab_size=256, n_layer=1, n_head=2, d_key=8,
                      d_value=8, d_model=16, d_inner=32)
        # 3 batch seqs want 3*ceil((8+inv_batch_new)/4) pages >> 24:
        # exhaustion mid-decode is guaranteed while batch runs
        engine = DecodeEngine(spec, max_batch=4, block_size=4,
                              num_blocks=24, pages_per_seq=16,
                              max_queue_depth=16)
        engine.warmup()
        engine.start()
        before = observe.snapshot()
        rng = np.random.RandomState(5)
        batch_streams = [
            engine.submit(rng.randint(0, 256, 8).tolist(),
                          max_new_tokens=inv_batch_new, seed=i,
                          tenant='bulk', priority='batch')
            for i in range(3)]
        time.sleep(0.25)       # let the batch class occupy the pool
        inter_streams = [
            engine.submit(rng.randint(0, 256, 8).tolist(),
                          max_new_tokens=inv_inter_new, seed=10 + i,
                          tenant='fg', priority='interactive')
            for i in range(2)]
        inter_lens = [len(s.result(timeout=300))
                      for s in inter_streams]
        batch_lens = [len(s.result(timeout=300))
                      for s in batch_streams]
        engine.shutdown(drain=True)
        snap = observe.snapshot()
        sel = lambda substr: (  # noqa: E731
            counter_sel(snap, 'tenant.preempted', substr)
            - counter_sel(before, 'tenant.preempted', substr))
        return {
            'scenario': 'inversion',
            'preempted_batch': sel('priority=batch'),
            'preempted_interactive': sel('priority=interactive'),
            'interactive_tokens': inter_lens,
            'batch_tokens': batch_lens,
        }

    inversion = run_inversion()

    # 4 — co-location: SLO pressure yields the trainer, calm resumes
    # it, params stay bit-identical to the uninterrupted run
    def make_batches():
        rng = np.random.RandomState(3)
        w = rng.randn(4, 1).astype('float32')
        r = np.random.RandomState(4)
        out = []
        for _ in range(train_batches):
            xs = r.randn(8, 4).astype('float32')
            out.append({'x': xs, 'y': xs @ w})
        return out

    def train_run(fluid, reader, hooks=None):
        """One fresh linreg training run; ``hooks(trainer)`` runs
        between construction and train() (the colo leg wires the
        controller there). Returns the final persistables."""
        from paddle_tpu import io as _io

        def train_func():
            x = fluid.layers.data(name='x', shape=[4],
                                  dtype='float32')
            y = fluid.layers.data(name='y', shape=[1],
                                  dtype='float32')
            pred = fluid.layers.fc(input=x, size=1)
            return [fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))]

        trainer = fluid.Trainer(
            train_func=train_func,
            optimizer_func=lambda: fluid.optimizer.SGD(
                learning_rate=0.1),
            place=fluid.CPUPlace())
        done = hooks(trainer) if hooks is not None else None
        trainer.train(num_epochs=1, event_handler=lambda e: None,
                      reader=reader)
        arrays, _ = _io._snapshot_vars(trainer.program,
                                       predicate=_io._is_persistable)
        arrays = {k: np.array(v) for k, v in arrays.items()}
        if done is not None:
            done()
        return arrays

    def run_colocation():
        batches = make_batches()
        base = train_run(_fresh(), lambda: iter(batches))

        gate_hit, gate_go = threading.Event(), threading.Event()

        def gated_reader():
            for i, b in enumerate(batches):
                if i == train_split:
                    gate_hit.set()
                    gate_go.wait(timeout=120)
                yield b

        tracker = SloTracker([Objective(
            'colo', latency_budget_s,
            availability_target=0.5, window_s=window_s)])
        engine = make_engine('colo0')
        engine.warmup()
        engine.start()
        # admission='none': the tracker must SEE every breach (burn is
        # the yield signal here) — SLO admission would shed the chaos
        # burst before it ever recorded a violation
        router = Router([engine], slo=tracker, route='colo',
                        admission='none')
        measured = {}

        fluid = _fresh()

        def hooks(trainer):
            pf, cf = colocation_yield(
                trainer, *slo_burn_pressure(tracker, 'colo'),
                route='colo')
            ctl = FleetController(router, make_engine, slo=tracker,
                                  route='colo', min_replicas=1,
                                  max_replicas=1, interval_s=tick_s,
                                  pressure_fn=pf, calm_fn=cf)
            ctl.start()

            def chaos():
                # trainer is mid-run, parked at the reader gate with
                # the pipeline drained of steps [0, train_split)
                gate_hit.wait(timeout=120)
                # burn the budget: every request breaches the 2ms
                # deadline by construction (8ms compute floor)
                rng = np.random.RandomState(11)
                for _ in range(20):
                    feed = {'x': rng.rand(1, in_dim)
                            .astype('float32')}
                    router.submit(feed, session='fg/s0').result(
                        timeout=30)
                t_flip = time.perf_counter()
                t_dead = t_flip + 5.0
                while time.perf_counter() < t_dead:
                    if observe.get_counter('tenant.trainer_yields_total',
                                           route='colo'):
                        measured['yield_latency_s'] = round(
                            time.perf_counter() - t_flip, 4)
                        break
                    time.sleep(0.002)
                gate_go.set()      # loop resumes, sees the request,
                t_dead = time.perf_counter() + 10.0   # drains, parks
                while time.perf_counter() < t_dead:
                    if trainer.yielded():
                        measured['parked'] = True
                        break
                    time.sleep(0.002)
                # calm: no more traffic — the violation window slides
                # out, burn drops, the controller resumes the trainer
                # (train() returning IS the resume evidence)

            th = threading.Thread(target=chaos, daemon=True)
            th.start()

            def done():
                th.join(timeout=60)
                measured['resumed'] = not trainer.yielded()
                ctl.close(shutdown_replicas=False)
            return done

        colo_params = train_run(fluid, gated_reader, hooks=hooks)
        engine.shutdown(drain=True)
        router.close()
        bit_identical = set(colo_params) == set(base) and all(
            np.array_equal(colo_params[k], base[k]) for k in base)
        return dict({
            'scenario': 'colocation',
            'train_steps': len(batches),
            'tick_s': tick_s,
            'bit_identical': bit_identical,
            'parked': measured.get('parked', False),
            'resumed': measured.get('resumed', False),
            'yield_latency_s': measured.get('yield_latency_s'),
        })

    colo = run_colocation()

    result = {
        'workload': 'multitenant',
        'noisy_neighbor': noisy,
        'quota_exhaustion': quota,
        'priority_inversion': inversion,
        'colocation': colo,
    }
    # the acceptance contract (ISSUE 18), asserted HERE
    assert isolation >= 0.9, \
        'noisy neighbor broke isolation: %r' % noisy
    assert mix_bg['quota_sheds'] > 0, \
        'batch flood was never shed: %r' % mix_bg
    assert acme['quota_sheds'] > 0 and acme['untyped_rejects'] == 0, \
        'over-quota sheds not typed QuotaExceededError: %r' % acme
    assert acme['lost'] == 0 and acme['errors'] == 0, \
        'in-quota traffic lost work: %r' % acme
    assert inversion['preempted_interactive'] == 0, \
        'interactive sequences were preempted: %r' % inversion
    assert inversion['preempted_batch'] > 0, \
        'pool pressure never preempted the batch class: %r' % inversion
    assert all(n == inv_inter_new
               for n in inversion['interactive_tokens']), \
        'interactive decode did not complete: %r' % inversion
    assert colo['yield_latency_s'] is not None and \
        colo['yield_latency_s'] <= tick_s + 0.2, \
        'trainer did not yield within a controller tick: %r' % colo
    assert colo['parked'] and colo['resumed'], \
        'trainer never parked/resumed around pressure: %r' % colo
    assert colo['bit_identical'], \
        'co-located training diverged from the solo run: %r' % colo
    return result


def bench_disagg(duration=5.0, clients=10, n_prefill=1, n_decode=2,
                 vocab=4000, n_layer=4, n_head=4, d_model=128,
                 d_inner=256, max_batch=8, block_size=16,
                 num_blocks=256, pages_per_seq=16,
                 long_prompt_frac=0.35, shared_prefix=0.6,
                 shared_prefix_len=32, ttft_budget_s=3.0,
                 kv_dtype=None, seed=0):
    """Disaggregated-vs-colocated fleet A/B at EQUAL total chip count
    (ISSUE 14's headline). Both legs run the same engines-per-fleet
    count (``n_prefill + n_decode``), the same weights, and the same
    mixed long-prompt/long-decode chaos mix (``loadgen.phase_mix``:
    a minority of prefill-heavy requests stall everything behind them
    on a colocated replica); the disaggregated leg splits the fleet
    into a prefill pool and a decode pool joined by the zero-copy KV
    handoff, the colocated leg serves both phases on every replica.
    Asserted here (and re-asserted by tests/test_handoff.py):

    - **inter-token p99**: disaggregated strictly below colocated —
      decode replicas never run a long prefill, so the inter-token
      tail collapses to the decode-step cadence plus a small suffix
      prefill.
    - **TTFT within budget**: the handoff hop (prefill elsewhere +
      packet install + suffix prefill) keeps p95 TTFT under
      ``ttft_budget_s``.
    - **lost == 0 on both fleets**: every accepted request completes.
    - **zero post-warmup executor cache misses on BOTH fleets**: the
      handoff installs pages between dispatches, the decode side's
      suffix prefill rides a warmed bucket — no new XLA signature on
      either side of the boundary.

    ``kv_dtype='int8'`` shrinks handoff wire bytes 3-4x (per-row
    scales ride in the packet); the returned ``handoff`` ledger
    reports measured bytes/page either way."""
    import threading

    from paddle_tpu import observe
    from paddle_tpu.serving import PhaseRouter, QueueFullError
    from paddle_tpu.serving.decode import (DecodeEngine, LMSpec,
                                           kv_page_bytes,
                                           random_weights)
    from paddle_tpu.serving.loadgen import (Stats, closed_loop,
                                            percentiles, phase_mix)

    d_head = max(8, d_model // n_head)
    spec = LMSpec(vocab_size=vocab, n_layer=n_layer, n_head=n_head,
                  d_key=d_head, d_value=d_head, d_model=d_model,
                  d_inner=d_inner)
    weights = random_weights(spec, seed=11)
    capacity = pages_per_seq * block_size
    # long prompts land in the TOP prefill bucket (a dispatch tens of
    # times a decode step's cost — the stall colocation suffers);
    # leave room for the long-prompt leg's short decode
    long_hi = capacity - 56
    shared_ids = np.random.RandomState(1234).randint(
        0, vocab, shared_prefix_len).tolist()

    def make_engine(name):
        return DecodeEngine(spec, max_batch=max_batch,
                            block_size=block_size,
                            num_blocks=num_blocks,
                            pages_per_seq=pages_per_seq,
                            max_queue_depth=8 * clients,
                            prefix_cache=True, kv_dtype=kv_dtype,
                            weights=weights, name=name)

    def misses(snap):
        return sum(v for k, v in snap['counters'].items()
                   if k.startswith('executor.cache_miss_total'))

    def counter_sum(snap, prefix):
        return sum(v for k, v in snap['counters'].items()
                   if k.startswith(prefix))

    def run_leg(tag, disagg):
        n_pre = n_prefill if disagg else 0
        n_dec = n_decode if disagg else n_prefill + n_decode
        pre = [make_engine('%s-pf%d' % (tag, i)) for i in range(n_pre)]
        dec = [make_engine('%s-dc%d' % (tag, i)) for i in range(n_dec)]
        for e in pre + dec:
            e.warmup()
            e.start()
        router = PhaseRouter(pre, dec, route=tag,
                             colocated=not disagg,
                             max_inflight=4 * clients)
        # the zero-recompile window opens AFTER warmup: anything from
        # here on is a live-traffic signature the invariant forbids
        snap0 = observe.snapshot()
        stats = Stats()
        mu = threading.Lock()
        gaps, ttfts = [], []
        accepted = [0]
        completed = [0]

        def do_request(rng):
            plen, max_new = phase_mix(
                rng, long_prompt_frac=long_prompt_frac,
                long_prompt=(long_hi - 32, long_hi))
            if rng.rand() < shared_prefix:
                tail = max(1, plen - shared_prefix_len)
                prompt = shared_ids + \
                    rng.randint(0, vocab, tail).tolist()
            else:
                prompt = rng.randint(0, vocab, plen).tolist()
            t_sub = time.perf_counter()
            stream = router.submit(prompt, max_new_tokens=max_new,
                                   seed=int(rng.randint(1 << 20)),
                                   session=int(rng.randint(0, 16)))
            with mu:
                accepted[0] += 1
            n, t_prev, local = 0, None, []
            t_first = None
            for _tok in stream:
                now = time.perf_counter()
                if t_first is None:
                    t_first = now
                if t_prev is not None:
                    local.append(now - t_prev)
                t_prev = now
                n += 1
            with mu:
                completed[0] += 1
                gaps.extend(local)
                if t_first is not None:
                    ttfts.append(t_first - t_sub)
            return n

        t0 = time.perf_counter()
        closed_loop(do_request, stats, t0 + duration, clients)
        router.close(shutdown_replicas=True)
        wall = time.perf_counter() - t0
        snap1 = observe.snapshot()
        return {
            'fleet': tag,
            'engines': n_pre + n_dec,
            'prefill_replicas': n_pre,
            'decode_replicas': n_dec,
            'duration_s': round(wall, 3),
            'requests_ok': stats.ok,
            'requests_rejected': stats.rejected,
            'requests_errored': stats.errors,
            'accepted': accepted[0],
            'completed': completed[0],
            'lost': accepted[0] - completed[0],
            'tokens': len(gaps) + len(ttfts),
            'inter_token_ms': percentiles(gaps),
            'ttft_ms': percentiles(ttfts),
            'request_ms': percentiles(stats.latencies),
            'post_warmup_cache_misses': misses(snap1) - misses(snap0),
            'handoffs': counter_sum(snap1, 'handoff.count_total')
            - counter_sum(snap0, 'handoff.count_total'),
            'handoff_pages_installed':
                counter_sum(snap1, 'handoff.pages_installed_total')
                - counter_sum(snap0, 'handoff.pages_installed_total'),
            'handoff_pages_deduped':
                counter_sum(snap1, 'handoff.pages_deduped_total')
                - counter_sum(snap0, 'handoff.pages_deduped_total'),
            'handoff_bytes':
                counter_sum(snap1, 'handoff.bytes_total')
                - counter_sum(snap0, 'handoff.bytes_total'),
            'preemptions':
                counter_sum(snap1, 'decode.preemptions_total')
                - counter_sum(snap0, 'decode.preemptions_total'),
        }

    # every engine in both legs builds the same three programs — ride
    # the AOT executable cache so engine #2..N deserialize their
    # prefill ladder instead of re-compiling it (the same trick the
    # autoscale bench uses for ~0.1s spawns)
    import tempfile
    prev = {k: os.environ.get(k) for k in
            ('PADDLE_TPU_AOT_CACHE', 'PADDLE_TPU_AOT_CACHE_DIR')}
    os.environ['PADDLE_TPU_AOT_CACHE'] = '1'
    os.environ['PADDLE_TPU_AOT_CACHE_DIR'] = \
        tempfile.mkdtemp(prefix='paddle_tpu_disagg_aot_')
    try:
        observe.flush(kind='snapshot')
        coloc = run_leg('coloc', disagg=False)
        observe.flush(kind='snapshot')
        split = run_leg('disagg', disagg=True)
        observe.flush(kind='snapshot')
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    p99_coloc = coloc['inter_token_ms'].get('p99')
    p99_disagg = split['inter_token_ms'].get('p99')
    ttft_p95 = split['ttft_ms'].get('p95')
    # the headline contract — each one a hard assertion, not a report
    assert coloc['lost'] == 0 and split['lost'] == 0, \
        'request loss: coloc=%d disagg=%d' % (coloc['lost'],
                                              split['lost'])
    assert coloc['post_warmup_cache_misses'] == 0, \
        'colocated fleet recompiled post-warmup: %d misses' \
        % coloc['post_warmup_cache_misses']
    assert split['post_warmup_cache_misses'] == 0, \
        'disaggregated fleet recompiled post-warmup: %d misses ' \
        '(the handoff must not mint signatures)' \
        % split['post_warmup_cache_misses']
    assert p99_coloc is not None and p99_disagg is not None, \
        'no inter-token samples'
    assert p99_disagg < p99_coloc, \
        'disaggregation did not beat colocated inter-token p99: ' \
        '%.2fms vs %.2fms' % (p99_disagg, p99_coloc)
    assert ttft_p95 is not None and \
        ttft_p95 <= ttft_budget_s * 1000.0, \
        'disagg TTFT p95 %.1fms blew the %.1fms budget' \
        % (ttft_p95 or -1, ttft_budget_s * 1000.0)
    assert split['handoffs'] > 0, 'no handoffs happened'

    from paddle_tpu.quant.core import resolve_kv_dtype
    kv = resolve_kv_dtype(kv_dtype)
    observe.set_gauge('disagg.inter_token_p99_ms', p99_disagg)
    observe.set_gauge('disagg.coloc_inter_token_p99_ms', p99_coloc)
    observe.set_gauge('disagg.ttft_p95_ms', ttft_p95)
    return {
        'workload': 'disagg',
        'colocated': coloc,
        'disaggregated': split,
        'inter_token_p99_improvement': round(p99_coloc / p99_disagg, 3)
        if p99_disagg else None,
        'ttft_budget_s': ttft_budget_s,
        'kv_dtype': kv,
        'page_wire_bytes': kv_page_bytes(spec, block_size, kv),
        'page_wire_bytes_fp32': kv_page_bytes(spec, block_size,
                                              'float32'),
        'traffic': {'clients': clients,
                    'long_prompt_frac': long_prompt_frac,
                    'shared_prefix': shared_prefix,
                    'shared_prefix_len': shared_prefix_len},
    }


def _build_resnet_step(batch, image, train=True):
    """One source of truth for the ResNet bench setup — the headline
    img/s (train=True) and the anatomy profile share it, so the
    anatomy numbers always explain the headline they sit beside."""
    fluid = _fresh()
    from paddle_tpu.models.resnet import resnet50_with_loss
    _, avg_cost, _ = resnet50_with_loss()
    if train:
        fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(
            avg_cost)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = _to_device(
        {'image': rng.rand(batch, 3, image, image).astype('float32'),
         'label': rng.randint(0, 1000, (batch, 1)).astype('int64')})
    return exe, feed, avg_cost


def bench_resnet50(batch=64, image=224, iters=20):
    exe, feed, avg_cost = _build_resnet_step(batch, image)

    if not _single_dispatch():
        return batch / _time_multi(exe, feed, [avg_cost], iters)

    def step():
        return exe.run(feed=feed, fetch_list=[avg_cost], return_numpy=False)

    dt = _time_steps(step, iters=iters)
    return batch / dt


def resnet_step_anatomy_phases(batch=64, image=224, iters=10):
    """ResNet-50 step anatomy (VERDICT r3 #2: the bwd gap): fwd-only
    vs full-step wall time on identical shapes, plus the compiled step's
    XLA cost analysis (flops / bytes accessed). detail math: if
    bytes_per_step / step_time approaches the chip's HBM bandwidth
    (~819 GB/s on v5e), the residual bwd gap is a memory-bandwidth
    floor, not a schedulable loss.

    Yields the growing dict once per phase — measured wall times first,
    cost analysis (a third full compile, the hang-prone part on the
    relay) last — so the caller can emit intermediate results that
    survive a watchdog kill."""
    import jax

    out = {'batch': batch}
    # fwd(+loss) only — no backward_marker in the program
    exe, feed, cost = _build_resnet_step(batch, image, train=False)
    out['fwd_ms'] = round(
        _time_multi(exe, feed, [cost], iters) * 1e3, 2)
    # full train step, same shapes
    exe, feed, cost = _build_resnet_step(batch, image, train=True)
    out['step_ms'] = round(
        _time_multi(exe, feed, [cost], iters) * 1e3, 2)
    out['bwd_update_ms'] = round(out['step_ms'] - out['fwd_ms'], 2)
    yield dict(out)

    # XLA cost analysis of the one-step compiled train fn
    try:
        fn, scope_vals, feed_vals = exe.compile_step(
            feed=feed, fetch_list=[cost])
        compiled = jax.jit(fn).lower(scope_vals, feed_vals,
                                     np.int32(0)).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get('flops', 0.0))
        byts = float(ca.get('bytes accessed', 0.0))
        out['xla_flops_per_step'] = flops
        out['xla_bytes_per_step'] = byts
        if out['step_ms'] > 0:
            out['achieved_tflops'] = round(
                flops / (out['step_ms'] * 1e-3) / 1e12, 1)
            out['achieved_hbm_gbps'] = round(
                byts / (out['step_ms'] * 1e-3) / 1e9, 1)
    except Exception as e:  # cost analysis is best-effort
        out['cost_analysis_error'] = str(e)[:200]
    yield out


def attention_microbench(batch_tokens=4096, d=64, heads=8, inner=8,
                         seqs=(1024, 4096)):
    """Direct fwd+bwd attention timing, XLA reference vs Pallas flash
    kernels, at the shapes the dispatch gate admits (seq >= 512, d_head
    64) — the dated on-chip table VERDICT r3 #8 asks for, isolated from
    the model (whose encoder/cross attention carries key_length and so
    never dispatches Pallas). `inner` grad steps run INSIDE one jitted
    fori_loop with inputs chained through the gradients, because the
    tunneled relay adds ~5 ms per dispatch and memoizes identical
    executions (SURVEY §5.1)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.attention_ops import reference_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    out = {}
    rng = np.random.RandomState(0)
    for seq in seqs:
        batch = max(1, batch_tokens // seq)
        shape = (batch, heads, seq, d)
        q0, k0, v0 = (jnp.asarray(rng.randn(*shape) * 0.1, jnp.bfloat16)
                      for _ in range(3))
        # masked legs (r5): per-example lengths at 75% of seq — the
        # variable-length NMT case; the Pallas kernel skips masked key
        # BLOCKS, so its masked leg should beat its dense one
        lens = jnp.full((batch,), max(1, (3 * seq) // 4), jnp.int32)
        legs = {'xla': lambda q, k, v: reference_attention(
                    q, k, v, causal=True),
                'pallas': lambda q, k, v: flash_attention(
                    q, k, v, causal=True),
                'xla_masked': lambda q, k, v: reference_attention(
                    q, k, v, causal=True, key_length=lens),
                'pallas_masked': lambda q, k, v: flash_attention(
                    q, k, v, causal=True, kv_len=lens)}
        for name, fn in legs.items():
            def loss(q, k, v, fn=fn):
                return fn(q, k, v).astype(jnp.float32).sum()

            grad_fn = jax.value_and_grad(loss, argnums=(0, 1, 2))

            def many(q, k, v, grad_fn=grad_fn):
                def body(_, carry):
                    q, k, v = carry
                    _, (dq, dk, dv) = grad_fn(q, k, v)
                    # chain grads into the inputs: defeats relay
                    # memoization without changing magnitudes much
                    return (q + 1e-3 * dq, k + 1e-3 * dk, v + 1e-3 * dv)

                return jax.lax.fori_loop(0, inner, body, (q, k, v))

            jmany = jax.jit(many)
            # warm-up compiles; its OUTPUTS feed the timed call — the
            # relay memoizes byte-identical executions (SURVEY §5.1),
            # so re-timing the same inputs would measure the relay.
            # Sync via np.asarray, NOT block_until_ready: on the relay
            # the latter returns at enqueue (_time_steps comment), and
            # timing it produced physically impossible sub-FLOP-floor
            # numbers (the original r4 capture's 0.014 ms "results").
            q1, k1, v1 = jmany(q0, k0, v0)
            np.asarray(q1)
            t0 = time.perf_counter()
            q2, k2, v2 = jmany(q1, k1, v1)
            np.asarray(q2)
            dt = (time.perf_counter() - t0) / inner
            out['seq%d_%s_fwdbwd_ms' % (seq, name)] = round(dt * 1e3, 3)
        xla = out['seq%d_xla_fwdbwd_ms' % seq]
        pal = out['seq%d_pallas_fwdbwd_ms' % seq]
        out['seq%d_winner' % seq] = 'pallas' if pal < xla * 0.98 else 'xla'
        xm = out['seq%d_xla_masked_fwdbwd_ms' % seq]
        pm = out['seq%d_pallas_masked_fwdbwd_ms' % seq]
        out['seq%d_masked_winner' % seq] = \
            'pallas' if pm < xm * 0.98 else 'xla'
    return out


def pallas_parity():
    """On-chip numerics of the Pallas kernels vs their XLA reference
    paths (VERDICT r2 weak #4: the kernels had never been parity-checked
    on real hardware). Returns {kernel: max_abs_err}."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                      _reference)
    from paddle_tpu.ops.pallas.layer_norm import (_ln_pallas, _ln_reference)

    rng = np.random.RandomState(0)
    b, h, t, d = 2, 4, 128, 64
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    out = {}
    for causal in (False, True):
        got = np.asarray(jax.jit(
            lambda q, k, v: flash_attention(q, k, v, causal=causal))(q, k, v))
        want = np.asarray(_reference(q, k, v, causal, d ** -0.5))
        out['flash_causal%d' % causal] = float(np.abs(got - want).max())
    x2 = jnp.asarray(rng.randn(512, 256), jnp.float32)
    gamma = jnp.asarray(rng.rand(256) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(256), jnp.float32)
    got = np.asarray(jax.jit(
        lambda x, g, b: _ln_pallas(x, g, b, 1e-5))(x2, gamma, beta))
    want = np.asarray(_ln_reference(x2, gamma, beta, 1e-5))
    out['layer_norm'] = float(np.abs(got - want).max())
    return out


def bench_autotune(seqs=(1024, 4096), batch_tokens=4096, d=64, heads=8,
                   iters=5, backend='cpu', child_timeout=240.0):
    """ISSUE 8: the autotuner + AOT warm-start A/B. Two phases:

    1. **tuned vs default-gated attention** at the BENCH_builder_r4
       shapes (seq 1024/4096, d_head 64): a fresh tuning table is
       measured in-process (PADDLE_TPU_AUTOTUNE=on), then the tuner's
       pick is timed against the env-gated default (XLA, since
       PADDLE_TPU_USE_PALLAS is unset). The r4 capture says the winner
       FLIPS between these shapes — `winners_differ` records whether
       this chip agrees, and the table lands beside the store for
       tools/tuning_inspect.py.
    2. **cold vs warm startup**: the same trainer-shaped program runs
       in two subprocesses sharing one fresh AOT cache dir
       (PADDLE_TPU_AOT_CACHE=1); the second should reach its first
       step on deserialized executables. Gauges
       aot.cold/warm_start_seconds land in the metrics JSONL so
       tools/metrics_report.py shows the win.
    """
    import tempfile
    import jax
    import jax.numpy as jnp
    from paddle_tpu import observe, tuning
    from paddle_tpu.ops.attention_ops import reference_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    out = {}
    tmp = tempfile.mkdtemp(prefix='paddle_tpu_autotune_')
    table_path = os.path.join(tmp, 'tuning.json')
    os.environ['PADDLE_TPU_TUNING_TABLE'] = table_path
    os.environ['PADDLE_TPU_AUTOTUNE'] = 'on'
    tuning.reset()
    rng = np.random.RandomState(0)

    def timed(fn, *args):
        np.asarray(fn(*args))           # compile + warm (relay sync)
        best = float('inf')
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    winners = []
    for seq in seqs:
        batch = max(1, batch_tokens // seq)
        shape = (batch, heads, seq, d)
        q, k, v = (jnp.asarray(rng.randn(*shape) * 0.1, jnp.bfloat16)
                   for _ in range(3))
        default_fn = jax.jit(
            lambda q, k, v: reference_attention(q, k, v, causal=True))
        picked = tuning.decide_attention(batch, heads, seq, seq, d,
                                         'bfloat16', True, False) or \
            {'impl': 'xla'}
        if picked.get('impl') == 'pallas':
            bq, bk = picked.get('block_q'), picked.get('block_k')
            tuned_fn = jax.jit(
                lambda q, k, v, bq=bq, bk=bk: flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk))
        else:
            tuned_fn = default_fn
        d_ms = timed(default_fn, q, k, v) * 1e3
        t_ms = timed(tuned_fn, q, k, v) * 1e3
        out['seq%d_default_ms' % seq] = round(d_ms, 3)
        out['seq%d_tuned_ms' % seq] = round(t_ms, 3)
        out['seq%d_winner' % seq] = picked.get('impl')
        winners.append(picked.get('impl'))
        observe.set_gauge('tuning.bench_speedup', d_ms / max(t_ms, 1e-9),
                          seq=seq)
    out['winners_differ'] = len(set(winners)) > 1
    out['table_entries'] = tuning.current_table().size()
    out['table_path'] = table_path

    # ---- phase 2: cold vs warm AOT startup (subprocess pair) ----
    cache_dir = os.path.join(tmp, 'aot_cache')
    env = dict(os.environ)
    env.update({'PADDLE_TPU_AOT_CACHE': '1',
                'PADDLE_TPU_AOT_CACHE_DIR': cache_dir})
    env.pop('PADDLE_TPU_METRICS_JSONL', None)   # children report via JSON
    cmd = [sys.executable, os.path.abspath(__file__),
           '--workload', 'autotune_child', '--backend', backend]

    def run_child():
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=child_timeout, env=env)
        except subprocess.TimeoutExpired:
            return None
        for line in reversed((r.stdout or '').splitlines()):
            if line.startswith('RESULT_JSON '):
                return json.loads(line[len('RESULT_JSON '):])
        return None

    cold = run_child()
    warm = run_child()
    if cold and warm:
        out['cold_start_seconds'] = cold['startup_seconds']
        out['warm_start_seconds'] = warm['startup_seconds']
        out['warm_from_disk_keys'] = warm['aot_hits']
        out['warm_compile_events'] = warm['compile_flight_events']
        observe.set_gauge('aot.cold_start_seconds',
                          cold['startup_seconds'])
        observe.set_gauge('aot.warm_start_seconds',
                          warm['startup_seconds'])
        observe.set_gauge('aot.warm_from_disk_keys', warm['aot_hits'])
    else:
        out['startup_ab_error'] = 'child failed or timed out'
    return out


def _autotune_startup_child():
    """One cold-or-warm startup measurement: build a trainer-shaped MLP
    program, run two steps, report wall from entry to the first fetch
    plus the executor's AOT ledger and the compile flight-event count
    (zero on a warm run — the acceptance check)."""
    from paddle_tpu import observe
    observe.arm_flight()    # count 'compile' events even with metrics off
    t0 = time.perf_counter()
    fluid = _fresh()
    x = fluid.layers.data(name='x', shape=[256], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = x
    for _ in range(4):
        h = fluid.layers.fc(input=h, size=256, act='relu')
    pred = fluid.layers.fc(input=h, size=1)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(
        input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    feed = {'x': np.ones((8, 256), 'float32'),
            'y': np.ones((8, 1), 'float32')}
    first = exe.run(feed=feed, fetch_list=[cost])
    startup = time.perf_counter() - t0
    np.asarray(exe.run(feed=feed, fetch_list=[cost])[0])
    compiles = sum(1 for e in observe.flight_recorder().events()
                   if e.get('kind') == 'compile')
    return {'startup_seconds': round(startup, 4),
            'first_loss': float(np.asarray(first[0]).reshape(())),
            'aot_hits': exe.aot_stats['hits'],
            'aot_saves': exe.aot_stats['saves'],
            'compile_flight_events': compiles}


def bench_verify(batch=8, seq=64, vocab=32000, iters=10):
    """ISSUE 9 overhead guard: the static verifier must stay noise next
    to the cold compile it precedes. Builds the transformer train
    program, times a full run of every analysis pass (best of `iters`
    — the verifier is pure Python over the op list), then times the
    COLD compile+first-step of the same program, and reports the
    ratio. Gauges analysis.verify_seconds /
    analysis.verify_vs_compile_ratio land in the metrics JSONL; `ok`
    is the acceptance bit (ratio < 1%)."""
    fluid = _fresh()
    from paddle_tpu import analysis, observe
    from paddle_tpu.models import transformer as T
    avg_cost, _ = T.transformer_base(
        src_vocab_size=vocab, trg_vocab_size=vocab,
        src_seq_len=seq, trg_seq_len=seq, max_length=max(256, seq))
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    prog = fluid.default_main_program()

    best = float('inf')
    diags = []
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        diags = analysis.run_passes(prog, fetch_names=[avg_cost.name])
        best = min(best, time.perf_counter() - t0)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    feed = _to_device(T.make_fake_batch(batch, seq, seq, vocab, vocab))
    t0 = time.perf_counter()
    out = exe.run(feed=feed, fetch_list=[avg_cost])
    np.asarray(out[0])
    cold = time.perf_counter() - t0

    ratio = best / cold if cold > 0 else float('inf')
    observe.set_gauge('analysis.verify_seconds', best)
    observe.set_gauge('analysis.verify_vs_compile_ratio', ratio)
    counts = analysis.summarize(diags)
    return {'verify_seconds': round(best, 6),
            'cold_compile_seconds': round(cold, 4),
            'verify_vs_compile_ratio': round(ratio, 6),
            'ops': len(prog.global_block().ops),
            'diagnostics': counts,
            'ok': bool(ratio < 0.01 and counts['error'] == 0)}


def bench_linalg(n_parity=256, tune_points=((512, 2048, 512),
                                            (256, 4096, 256)),
                 n_fact=256, n_pow=1024, powit_iters=40, runs=5,
                 reduced=False):
    """Distributed linear algebra at pod scale (ISSUE 15), four
    asserted legs over the dp x tp mesh:

    1. **SUMMA parity + zero recompiles** — blocked matmul matches
       numpy at the parity shape; after the first (compiling) run,
       `runs` more dispatches hit the executor cache with ZERO misses.
    2. **autotuned panel** — PADDLE_TPU_AUTOTUNE=record sweeps the
       legal panel ladder at each (N, K, M) tuning point; asserts the
       recorded winner STRICTLY beats the default panel's measured
       time on at least one point (the r4 lesson: no single panel is
       right for every shape), then asserts the memory contract —
       per-shard peak arena bytes within 1.5x of the O(N^2/P) ideal —
       at the LARGEST SUMMA shape with its default panel.
    3. **blocked Cholesky / QR** — factorization residuals
       (reconstruction, orthogonality, triangularity) at n_fact on a
       1-D dp mesh.
    4. **power iteration** — dominant eigenvalue matches numpy to
       rel-err < 1e-3 through exact psum and < 5e-2 through the PR 13
       quantized allreduce, with the analytic wire-bytes compression
       >= 3x reported from the linalg.powit_* gauges. The reduction IS
       the step here, which is what makes this the second measurement
       axis for the compressed-collective trade.
    """
    import jax

    from paddle_tpu import linalg, observe, tuning
    from paddle_tpu.core.executor import Executor
    from paddle_tpu.parallel.mesh import make_mesh

    if reduced:
        n_parity, n_fact, n_pow = 128, 128, 512
        tune_points = ((256, 2048, 256), (128, 4096, 128))
        powit_iters, runs = 30, 3

    count = jax.device_count()
    dp = 2 if count >= 2 else 1
    tp = max(1, min(4, count // dp))
    while tp > 1 and count < dp * tp:
        tp //= 2
    grid = make_mesh(dp=dp, tp=tp)
    dp1 = 1
    while dp1 * 2 <= min(8, count):
        dp1 *= 2
    line = make_mesh(dp=dp1)
    out = {'workload': 'linalg', 'grid': {'dp': dp, 'tp': tp},
           'line_dp': dp1}
    rng = np.random.RandomState(0)

    # ---- leg 1: SUMMA parity + zero recompiles ---------------------
    n = n_parity
    a = rng.randn(n, n).astype('float32')
    b = rng.randn(n, n).astype('float32')
    exe = Executor()
    prog, c_var = linalg.build_matmul_program(n, n, n, mesh=grid,
                                             panel=32)
    t0 = time.perf_counter()
    got = exe.run(prog, feed={'summa_x': a, 'summa_y': b},
                  fetch_list=[c_var])[0]
    first = time.perf_counter() - t0
    ref = a.astype('float64') @ b.astype('float64')
    rel = float(np.abs(got - ref).max() / np.abs(ref).max())
    assert rel < 1e-4, 'SUMMA parity rel err %.2e' % rel
    snap = observe.snapshot()
    miss0 = sum(v for k, v in snap.get('counters', {}).items()
                if k.startswith('executor.cache_miss_total'))
    best = float('inf')
    for _ in range(runs):
        t0 = time.perf_counter()
        np.asarray(exe.run(prog, feed={'summa_x': a, 'summa_y': b},
                           fetch_list=[c_var])[0])
        best = min(best, time.perf_counter() - t0)
        assert not exe.last_cache_miss, \
            'SUMMA warm dispatch missed the compile cache'
    snap = observe.snapshot()
    miss1 = sum(v for k, v in snap.get('counters', {}).items()
                if k.startswith('executor.cache_miss_total'))
    assert miss1 == miss0, 'cache misses after warmup: %d' \
        % (miss1 - miss0)
    gf = 2.0 * n * n * n / best / 1e9
    out['summa'] = {'n': n, 'rel_err': rel,
                    'first_dispatch_s': round(first, 4),
                    'warm_step_s': round(best, 5),
                    'gflops': round(gf, 2),
                    'cache_misses_after_warmup': 0}
    observe.set_gauge('linalg.bench_summa_gflops', gf)

    # ---- leg 2: autotuned panel vs default + memory contract -------
    tune_dir = os.environ.get('TMPDIR', '/tmp')
    table_path = os.path.join(tune_dir, 'bench_linalg_tuning_%d.json'
                              % os.getpid())
    saved = {k: os.environ.get(k) for k in ('PADDLE_TPU_AUTOTUNE',
                                            'PADDLE_TPU_TUNING_TABLE')}
    os.environ['PADDLE_TPU_AUTOTUNE'] = 'record'
    os.environ['PADDLE_TPU_TUNING_TABLE'] = table_path
    tuning.reset()
    try:
        points = []
        beats = 0
        for (pn, pk, pm) in tune_points:
            win = tuning.decide_summa_panel(pn, pk, pm, 'float32', grid)
            default = linalg.default_panel(pk, dp, tp, n=pn, m=pm)
            key = ('summa_matmul|n%d k%d m%d|dp%d tp%d|float32'
                   % (pn, pk, pm, dp, tp))
            ent = tuning.current_table().lookup(tuning.device_kind(),
                                                key)
            timings = {k: v for k, v in ent['timings'].items()
                       if v >= 0}
            def_label = 'summa panel%d' % default
            win_label = 'summa panel%d' % int(win['panel'])
            t_def = timings.get(def_label)
            t_win = timings.get(win_label)
            strictly = (win['panel'] != default and t_def is not None
                        and t_win is not None and t_win < t_def)
            beats += bool(strictly)
            points.append({
                'shape': [pn, pk, pm], 'default_panel': default,
                'tuned_panel': int(win['panel']),
                'default_ms': round(t_def * 1e3, 3) if t_def else None,
                'tuned_ms': round(t_win * 1e3, 3) if t_win else None,
                'tuned_beats_default': strictly})
        assert beats >= 1, \
            'autotuned panel never beat the default: %r' % points
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        tuning.reset()
    # memory contract at the LARGEST SUMMA shape, default panel
    big = max(tune_points, key=lambda d: d[0] * d[1] + d[1] * d[2])
    model = linalg.assert_memory_contract(
        'summa_matmul', grid, big, panel=linalg.default_panel(
            big[1], dp, tp, n=big[0], m=big[2]), factor=1.5)
    observe.set_gauge('linalg.bench_memory_factor', model['factor'])
    out['autotune'] = {'points': points, 'tuned_beats_default': beats}
    out['memory'] = {'shape': list(big), 'per_shard_peak': model['peak'],
                     'ideal': model['ideal'],
                     'factor': round(model['factor'], 3),
                     'participants': model['participants']}

    # ---- leg 3: blocked Cholesky / QR residuals --------------------
    nf = n_fact
    m0 = rng.randn(nf, nf).astype('float32')
    spd = (m0 @ m0.T + nf * np.eye(nf)).astype('float32')
    exe3 = Executor()
    l = np.asarray(linalg.cholesky(spd, mesh=line, executor=exe3))
    chol_res = float(np.abs(l @ l.T - spd).max() / np.abs(spd).max())
    assert chol_res < 1e-5, 'cholesky residual %.2e' % chol_res
    assert float(np.abs(np.triu(l, 1)).max()) == 0.0

    tall = rng.randn(nf * 2, nf).astype('float32')
    q, r = linalg.qr(tall, mesh=line, executor=exe3)
    q, r = np.asarray(q), np.asarray(r)
    orth = float(np.abs(q.T @ q - np.eye(nf)).max())
    recon = float(np.abs(q @ r - tall).max() / np.abs(tall).max())
    assert orth < 1e-4, 'QR orthogonality %.2e' % orth
    assert recon < 1e-4, 'QR reconstruction %.2e' % recon
    out['factorizations'] = {
        'n': nf, 'dp': dp1,
        'cholesky_residual': chol_res,
        'qr_orthogonality': orth, 'qr_reconstruction': recon}

    # ---- leg 4: power iteration, exact vs quantized reduction ------
    npow = n_pow
    qo, _ = np.linalg.qr(rng.randn(npow, npow))
    spectrum = np.concatenate([[10.0, 6.0],
                               np.linspace(1.0, 2.0, npow - 2)])
    sym = ((qo * spectrum) @ qo.T).astype('float32')
    sym = (sym + sym.T) / 2
    dom = np.linalg.eigvalsh(sym)
    dom = float(dom[np.abs(dom).argmax()])
    exe4 = Executor()
    lam, _ = linalg.power_iteration(sym, iters=powit_iters, mesh=line,
                                    executor=exe4)
    assert not exe4.last_cache_miss, \
        'power_iteration re-compiled inside the loop'
    rel_exact = abs(lam - dom) / abs(dom)
    assert rel_exact < 1e-3, \
        'power iteration (psum) rel err %.2e' % rel_exact
    lam_q, _ = linalg.power_iteration(sym, iters=powit_iters,
                                      mesh=line, quantized=True,
                                      executor=exe4)
    rel_quant = abs(lam_q - dom) / abs(dom)
    assert rel_quant < 5e-2, \
        'power iteration (quantized) rel err %.2e' % rel_quant
    g = observe.snapshot().get('gauges', {})
    compression = g.get('linalg.powit_compression', 0.0)
    if dp1 > 1:
        assert compression >= 3.0, \
            'quantized reduction compression %.2fx < 3x' % compression
    out['power_iteration'] = {
        'n': npow, 'iters': powit_iters, 'numpy_eigval': dom,
        'exact': {'eigval': lam, 'rel_err': rel_exact},
        'quantized': {'eigval': lam_q, 'rel_err': rel_quant,
                      'compression_x': round(compression, 2),
                      'bytes_fp32': g.get('linalg.powit_bytes_fp32'),
                      'bytes_quant': g.get('linalg.powit_bytes_quant')},
    }
    observe.set_gauge('linalg.bench_powit_rel_err_exact', rel_exact)
    observe.set_gauge('linalg.bench_powit_rel_err_quant', rel_quant)
    out['ok'] = True
    return out


def _run_workload_child(workload, backend, reduced):
    """Child-process entry: run ONE workload, print 'RESULT <number>'."""
    from paddle_tpu import observe
    # metrics JSONL beside the result lines; summary line lands via the
    # atexit hook even when a later phase hangs and the watchdog kills us.
    # The AOT cost probe (~doubles each compile) stays off by default
    # here: relay watchdog budgets are tight and bench computes its MFU
    # analytically; executor.first_dispatch_seconds still records
    # per-key compile wall for free. Opt back in with
    # PADDLE_TPU_OBSERVE_COST=1.
    os.environ.setdefault('PADDLE_TPU_OBSERVE_COST', '0')
    observe.enable(jsonl=_metrics_path(),
                   trace=os.environ.get('PADDLE_TPU_TRACE_JSON'))
    if backend == 'cpu':
        from paddle_tpu.core.platform_boot import force_host_cpu
        # the quant/linalg ablations need a dp(x tp) mesh even
        # off-chip: 8 virtual CPU devices, same as the test conftest
        force_host_cpu(8 if workload in ('quant', 'linalg', 'trainspeed')
                       else None)
    # one home for the cache-arming quirk (env alone does not arm it on
    # this jax build); a workload killed mid-compile then restarts from
    # the cached executable instead of re-burning its watchdog budget
    from paddle_tpu.core.platform_boot import arm_compile_cache
    arm_compile_cache()
    if workload == 'pallas_parity':
        print('RESULT_JSON %s' % json.dumps(pallas_parity()), flush=True)
        return
    if workload == 'autotune':
        kw = dict(seqs=(512,), batch_tokens=512, iters=2,
                  child_timeout=180.0) if reduced else {}
        if backend == 'cpu':
            os.environ.setdefault('PADDLE_TPU_PALLAS_INTERPRET', '1')
        print('RESULT_JSON %s'
              % json.dumps(bench_autotune(backend=backend, **kw)),
              flush=True)
        return
    if workload == 'autotune_child':
        print('RESULT_JSON %s' % json.dumps(_autotune_startup_child()),
              flush=True)
        return
    if workload == 'verify':
        kw = dict(batch=2, seq=16, vocab=512, iters=3) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_verify(**kw)),
              flush=True)
        return
    if workload == 'resnet50_anatomy':
        kw = dict(batch=4, image=64, iters=3) if reduced else {}
        # emitted per-phase: the wall-time split prints before the
        # best-effort cost analysis, so a compile hang in the latter
        # can't take the measured numbers down with the watchdog (the
        # parent keeps the LAST complete line it sees)
        for partial in resnet_step_anatomy_phases(**kw):
            print('RESULT_JSON %s' % json.dumps(partial), flush=True)
        return
    if workload == 'attention_microbench':
        kw = {}
        if reduced:
            kw = dict(batch_tokens=512, inner=2, seqs=(512,))
        if backend == 'cpu':
            # CPU leg (smoke only): run the Pallas kernels in interpret
            # mode — the numbers are meaningless off-chip anyway
            os.environ.setdefault('PADDLE_TPU_PALLAS_INTERPRET', '1')
        print('RESULT_JSON %s' % json.dumps(attention_microbench(**kw)),
              flush=True)
        return
    if workload in ('pipeline_transformer', 'pipeline_resnet50'):
        model = 'transformer' if workload.endswith('transformer') \
            else 'resnet50'
        if reduced:
            kw = dict(steps=6, batch=8, seq=16, vocab=512) \
                if model == 'transformer' else \
                dict(steps=4, batch=2, image=32)
        else:
            kw = {}
        print('RESULT_JSON %s'
              % json.dumps(bench_pipeline_ablation(model, **kw)),
              flush=True)
        return
    if workload == 'decode_transformer':
        kw = dict(duration=2.0, clients=3, max_batch=4, block_size=8,
                  num_blocks=64, pages_per_seq=8, vocab=512, n_layer=2,
                  n_head=2, d_model=32, d_inner=64, prompt_lo=2,
                  prompt_hi=16, max_new=16) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_decode(**kw)),
              flush=True)
        return
    if workload == 'fleet':
        kw = dict(duration=3.0, steady_qps=30.0, spike_qps=700.0,
                  spike_at=1.0, spike_s=1.0, kill_at=1.2,
                  window_s=1.0, max_queue_depth=8) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_fleet(**kw)),
              flush=True)
        return
    if workload == 'autoscale':
        kw = dict(flash_duration=3.0, crash_duration=3.5,
                  trough_duration=3.5, window_s=1.0) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_autoscale(**kw)),
              flush=True)
        return
    if workload == 'crosshost':
        kw = dict(kill_duration=6.0, hung_duration=8.0,
                  crash_duration=9.0, crash_kills=2,
                  identity_requests=6) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_crosshost(**kw)),
              flush=True)
        return
    if workload == 'multitenant':
        # inv_batch_new must overshoot the 24-page pool: 3 batch seqs
        # * ceil((8+28)/4) = 27 pages (24 would fit exactly — no
        # exhaustion, no preemption to measure)
        kw = dict(mix_duration=1.5, quota_duration=1.5,
                  inv_batch_new=28, train_batches=8) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_multitenant(**kw)),
              flush=True)
        return
    if workload == 'quant':
        kw = dict(steps=60, kv_duration=1.5, fleet_duration=3.0,
                  reduced=True) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_quant(**kw)),
              flush=True)
        return
    if workload == 'trainspeed':
        kw = dict(steps=20, mfu_iters=2, reduced=True) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_trainspeed(**kw)),
              flush=True)
        return
    if workload == 'linalg':
        print('RESULT_JSON %s'
              % json.dumps(bench_linalg(reduced=reduced)), flush=True)
        return
    if workload == 'disagg':
        # reduced: small model but LONG capacity (pages_per_seq=32 ->
        # 512-token prompts), so the top prefill bucket still costs
        # tens of decode steps — the stall the A/B measures
        kw = dict(duration=2.5, clients=6, vocab=2048, n_layer=2,
                  n_head=4, d_model=64, d_inner=128,
                  pages_per_seq=32, num_blocks=256) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_disagg(**kw)),
              flush=True)
        return
    if workload == 'transformer_seq512_masked':
        kw = dict(batch=2, seq=512, vocab=4096, iters=3) if reduced else {}
        print('RESULT_JSON %s' % json.dumps(bench_transformer_masked(**kw)),
              flush=True)
        return
    if workload == 'transformer':
        kw = dict(batch=8, seq=32, vocab=4096, iters=5) if reduced else {}
        val = bench_transformer(**kw)
    elif workload == 'transformer_seq256':
        # long-sequence config (SURVEY §7.10): same 4096 tokens/step as
        # the base config so the two tok/s numbers are comparable.
        kw = dict(batch=2, seq=256, vocab=4096, iters=5) if reduced \
            else dict(batch=16, seq=256)
        val = bench_transformer(**kw)
    elif workload == 'transformer_seq1024':
        # long-seq config where the flash-attention gate actually
        # dispatches: seq >= 512, d_head 64, AND dropout 0 (the gate
        # requires it — attention-output dropout would block the
        # kernel). The honest on-chip fwd+bwd Pallas-vs-XLA comparison
        # runs here (VERDICT r3 #8); both legs share dropout=0 so the
        # comparison is attention-path-only.
        kw = dict(batch=1, seq=1024, vocab=4096, iters=3) if reduced \
            else dict(batch=4, seq=1024, iters=10)
        val = bench_transformer(dropout=0.0, **kw)
    elif workload == 'rnn_lstm':
        kw = dict(batch=8, seq=16, vocab=512, iters=3) if reduced else {}
        val = bench_rnn_lstm(**kw)
    elif workload == 'transformer_big':
        # the reference benchmark suite's other NMT config (d_model
        # 1024 / 16 heads / d_inner 4096); watcher-queue workload —
        # not in the default driver ablations (budget)
        kw = dict(batch=4, seq=32, vocab=4096, iters=3) if reduced \
            else dict(batch=32, seq=64, iters=10)
        val = bench_transformer(big=True, **kw)  # canonical dropout 0.3
    elif workload == 'transformer_seq4096':
        # longest-context config (batch 1 holds tokens/step at 4096);
        # dropout 0 keeps the Pallas gate open, same as seq1024.
        # reduced keeps seq=4096 (the label IS the sequence length —
        # shrinking it would invert the long-context comparison) and
        # cuts vocab/iters instead.
        kw = dict(batch=1, seq=4096, vocab=4096, iters=2) if reduced \
            else dict(batch=1, seq=4096, iters=8)
        val = bench_transformer(dropout=0.0, **kw)
    elif workload.startswith('moe_cap'):
        cap = float(workload[len('moe_cap'):])
        kw = dict(batch=4, seq=16, vocab=512, num_experts=4, n_layer=2,
                  iters=3) if reduced else {}
        val = bench_moe(capacity_factor=cap, **kw)
    else:
        kw = dict(batch=4, image=64, iters=5) if reduced else {}
        val = bench_resnet50(**kw)
    print('RESULT %r' % val, flush=True)


def _run_workload(workload, backend, reduced, timeout, env=None):
    """Run one workload in a watchdogged subprocess: a relay that answers
    the probe then hangs mid-run (documented failure mode) must not take
    the whole bench down with no JSON printed. Returns (value, error);
    value is a dict for RESULT_JSON workloads."""
    cmd = [sys.executable, os.path.abspath(__file__),
           '--workload', workload, '--backend', backend]
    if reduced:
        cmd.append('--reduced')
    child_env = dict(os.environ)
    child_env.update(env or {})
    def last_result(stdout):
        for line in reversed((stdout or '').splitlines()):
            if line.startswith('RESULT_JSON '):
                return json.loads(line[len('RESULT_JSON '):])
            if line.startswith('RESULT '):
                return float(line[len('RESULT '):])
        return None

    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, env=child_env)
    except subprocess.TimeoutExpired as e:
        # the child may have printed a (partial) result before hanging
        # in a later best-effort phase — salvage it rather than lose
        # measured numbers to the watchdog
        stdout = e.stdout.decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or '')
        val = last_result(stdout)
        if val is not None:
            return val, None
        return None, 'timeout after %.0fs' % timeout
    val = last_result(r.stdout)
    if val is not None:
        return val, None
    return None, ('rc=%s: %s' % (r.returncode,
                                 (r.stderr or '').strip()[-800:]))


def main():
    t_start = time.time()
    # Persistent XLA compile cache, inherited by every workload child: a
    # re-run of a workload that previously timed out mid-compile starts
    # from the cached executable instead of burning its watchdog budget
    # on the same compile. Harmless where the backend ignores it.
    os.environ.setdefault('JAX_COMPILATION_CACHE_DIR',
                          '/tmp/paddle_tpu_jax_cache')
    # every workload child writes telemetry here (inherited env)
    os.environ.setdefault('PADDLE_TPU_METRICS_JSONL', _metrics_path())
    forced = os.environ.get('BENCH_BACKEND')
    if forced:
        backend, degraded = forced, False
    else:
        backend, degraded = _probe_backend()
        if degraded:
            sys.stderr.write('bench: TPU backend unavailable after '
                             'retries; falling back to cpu with reduced '
                             'shapes\n')
    # Reduced shapes only in the unplanned-degradation case (flaky relay
    # inside a fixed driver budget); a deliberate BENCH_BACKEND=cpu run or
    # a genuinely-cpu machine keeps full shapes unless BENCH_REDUCED=1.
    reduced = degraded or os.environ.get('BENCH_REDUCED') == '1'
    timeout = 200.0 if reduced else 250.0

    tok_s = img_s = None
    errors = {}
    ablations = {}
    captured = set()   # keys measured OK by THIS run
    masked_head = None
    state = {'relay_dead': False}
    on_chip = backend not in ('cpu',)

    def run_rec(key, workload, tout, env=None):
        """One watchdogged workload, persisted to the shared store the
        moment it finishes (the resumable-queue contract: a bench run
        killed mid-queue loses nothing already measured). A failure on
        the chip backend triggers a 25s quick probe; if that fails too,
        the relay is dead and the queue stops instead of burning every
        remaining watchdog against it."""
        val, err = _run_workload(workload, backend, reduced, tout, env=env)
        store_put(key, workload, backend, value=val, ok=err is None,
                  env=env,
                  provenance=os.environ.get('BENCH_PROVENANCE', 'driver'),
                  error=err)
        if err:
            errors[key] = err
            if on_chip and not _probe_quick():
                state['relay_dead'] = True
                errors['relay'] = 'died mid-run (quick probe failed)'
        else:
            captured.add(key)
        return val, err

    def alive():
        return not state['relay_dead']

    tok_s, err = run_rec('transformer', 'transformer', timeout)
    if err:
        sys.stderr.write('bench: transformer failed: %s\n' % err)
    if alive():
        img_s, err = run_rec('resnet50', 'resnet50', timeout)
        if err:
            sys.stderr.write('bench: resnet50 failed: %s\n' % err)

    # Ablations (SURVEY §5.1): conv layout, BN compute dtype, dispatch
    # mode, seq-256, scan-over-layers, the threefry-PRNG cost, plus
    # on-chip kernel parity. Skipped on a degraded relay — the budget
    # belongs to the headline numbers then — and stopped once the total
    # wall budget is spent (a hanging relay must not starve the JSON
    # line).
    budget = float(os.environ.get('BENCH_TOTAL_BUDGET', '2000'))

    def over_budget(extra=0.0):
        if time.time() - t_start > budget - timeout - extra:
            errors.setdefault('ablations', 'skipped: wall budget spent')
            return True
        return False

    if not reduced and os.environ.get('BENCH_ABLATIONS', '1') != '0':
        # Priority order (VERDICT r4 next-#1): fused-CE A/B → s2d A/B →
        # step anatomy → MoE sweep → the FIXED attention microbench —
        # the five measurements no driver run has ever captured — then
        # the seq-1024 pair, the ResNet layout/BN A/Bs, dispatch-mode,
        # the seq-4096 pair, and the long-standing sweeps. Every result
        # lands in the shared store the moment it exists; a mid-queue
        # relay death stops the chain (run_rec's quick probe) and the
        # final JSON carries whatever was measured.
        if alive() and not over_budget(extra=100.0):
            # masked seq-512 CO-HEADLINE (VERDICT r4 next-#4): the
            # variable-length NMT shape with an MFU figure; surfaces in
            # detail['masked_seq512'], not buried in ablations
            masked, err = run_rec('transformer_seq512_masked',
                                  'transformer_seq512_masked',
                                  timeout + 100)
            if not err:
                masked_head = masked
                if alive() and on_chip and not over_budget(extra=100.0):
                    # the Pallas leg: masked batches now dispatch the
                    # flash kernel (kv_len support) — the A/B this
                    # round's kernel rework is accountable to
                    maskedp, err = run_rec(
                        'transformer_seq512_masked_pallas',
                        'transformer_seq512_masked', timeout + 100,
                        env={'PADDLE_TPU_USE_PALLAS': '1'})
                    if not err:
                        ablations['masked_seq512_pallas'] = maskedp
                        ablations['masked_seq512_winner'] = \
                            'pallas' if maskedp['tok_per_sec'] > \
                            masked['tok_per_sec'] * 1.02 else 'xla'
        if alive() and not over_budget():
            # custom_vjp fused CE (r4): ablation restores the
            # materializing log_softmax form for the A/B
            tok_nce, err = run_rec('transformer_naive_ce', 'transformer',
                                   timeout, env={'PADDLE_TPU_FUSED_CE': '0'})
            if not err:
                ablations['transformer_tok_per_sec_naive_ce'] = \
                    round(tok_nce, 1)
        layout_env = {}
        if on_chip and alive() and not over_budget():
            # space-to-depth stem rewrite (r4): exact-math 4x4 s1 conv
            # over 2x2-stacked planes instead of the Cin=3 7x7 s2 stem.
            # Gated on the NHWC-native network — the TPU default.
            img_s2d, err = run_rec('resnet50_s2d_stem', 'resnet50',
                                   timeout, env={'PADDLE_TPU_CONV_S2D': '1'})
            if not err:
                ablations['resnet50_img_per_sec_s2d_stem'] = round(
                    img_s2d, 1)
                if img_s is not None and img_s2d > img_s * 1.02:
                    ablations['resnet50_stem_winner'] = 's2d'
                    layout_env = {'PADDLE_TPU_CONV_S2D': '1'}
                    img_s = img_s2d
                elif img_s is not None:
                    ablations['resnet50_stem_winner'] = 'direct'
        if on_chip and alive() and not over_budget():
            # one-pass Pallas BN (r5, VERDICT r4 next-#2): stats +
            # normalize in one kernel vs XLA's fusion choices
            img_bnp, err = run_rec('resnet50_bn_pallas', 'resnet50',
                                   timeout,
                                   env=dict(layout_env,
                                            PADDLE_TPU_BN_PALLAS='1'))
            if not err:
                ablations['resnet50_img_per_sec_bn_pallas'] = round(
                    img_bnp, 1)
                if img_s is not None and img_bnp > img_s * 1.02:
                    ablations['resnet50_bn_kernel_winner'] = 'pallas'
                    layout_env = dict(layout_env,
                                      PADDLE_TPU_BN_PALLAS='1')
                    img_s = img_bnp
                elif img_s is not None:
                    ablations['resnet50_bn_kernel_winner'] = 'xla'
        if on_chip and alive() and not over_budget(extra=150.0):
            # fwd/bwd wall split + XLA cost analysis: decides whether
            # the ResNet fwd gap closed (VERDICT r4 next-#2)
            anatomy, err = run_rec('resnet50_anatomy', 'resnet50_anatomy',
                                   timeout + 100)
            if not err:
                ablations['resnet50_step_anatomy'] = anatomy
        if on_chip and alive():
            # MoE capacity-factor sweep: throughput at cap 1.0/1.25/2.0 —
            # tighter capacity drops more tokens but dispatches less.
            # MoE compile is the slow part (r4 capture: 250 s timeouts
            # before first result) — compile-heavy slack on each.
            moe_sweep = {}
            for cap in ('1.0', '1.25', '2.0'):
                if not alive() or over_budget(extra=150.0):
                    break
                tok_moe, err = run_rec('moe_cap' + cap, 'moe_cap' + cap,
                                       timeout + 150)
                if not err:
                    moe_sweep['tok_per_sec_cap' + cap] = round(tok_moe, 1)
            if moe_sweep:
                # record which layer-stacking mode produced the numbers
                # (scan vs unrolled throughput differ; cross-round
                # comparisons must not conflate mode with routing cost)
                moe_sweep['layer_mode'] = 'scan' if os.environ.get(
                    'BENCH_MOE_SCAN', '1') != '0' else 'unrolled'
                ablations['moe_capacity_sweep'] = moe_sweep
        if on_chip and alive() and not over_budget():
            # isolated fwd+bwd attention, XLA vs Pallas, seq 1024/4096
            # d_head 64 — the np.asarray-synced FIX of the retracted r4
            # numbers (its own watchdog: relay Pallas compiles hang)
            attn, err = run_rec('attention_microbench',
                                'attention_microbench', timeout)
            if not err:
                ablations['attention_fwdbwd_microbench'] = attn
        # Pallas gets its honest e2e shot at seq 1024 where the dispatch
        # gate is actually open (seq >= 512, d_head 64); at the
        # headline's seq 64 the gate never dispatches, so an ablation
        # there would measure the identical XLA path.
        if on_chip and alive() and not over_budget(extra=timeout + 200.0):
            tok_1k, err = run_rec('transformer_seq1024',
                                  'transformer_seq1024', timeout + 100)
            if not err:
                ablations['transformer_tok_per_sec_seq1024'] = \
                    round(tok_1k, 1)
                if alive() and not over_budget(extra=100.0):
                    tok_1kp, err = run_rec(
                        'transformer_seq1024_pallas', 'transformer_seq1024',
                        timeout + 100, env={'PADDLE_TPU_USE_PALLAS': '1'})
                    if not err:
                        ablations['transformer_tok_per_sec_seq1024_pallas'] \
                            = round(tok_1kp, 1)
                        ablations['seq1024_attention_winner'] = \
                            'pallas' if tok_1kp > tok_1k * 1.02 else 'xla'
        if on_chip and alive() and not over_budget():
            # default on TPU is the IR-native NHWC network; this
            # ablation measures the old NCHW-IR form (whose lowering
            # still applies the per-conv NHWC trick) and still promotes
            # it if it wins (cpu default is already NCHW-IR)
            img_nchw, err = run_rec(
                'resnet50_nchw_ir', 'resnet50', timeout,
                env=dict(layout_env, PADDLE_TPU_RESNET_LAYOUT='NCHW'))
            if not err:
                ablations['resnet50_img_per_sec_nchw_ir'] = round(
                    img_nchw, 1)
                if img_s is not None and img_nchw > img_s:
                    ablations['resnet50_layout_winner'] = 'NCHW_IR'
                    layout_env = dict(layout_env,
                                      PADDLE_TPU_RESNET_LAYOUT='NCHW')
                    # the s2d stem is NHWC-gated — inert under NCHW
                    layout_env.pop('PADDLE_TPU_CONV_S2D', None)
                    img_s = img_nchw  # headline takes the faster layout
                elif img_s is not None:
                    ablations['resnet50_layout_winner'] = 'NHWC_IR'
        if alive() and not over_budget() and \
                'PADDLE_TPU_BN_PALLAS' not in layout_env:
            # carries the winning stem/layout so only BN compute differs;
            # skipped when the Pallas BN kernel won — that path pins its
            # own dtypes and would ignore PADDLE_TPU_BN_COMPUTE
            img_bn, err = run_rec(
                'resnet50_bn_fp32', 'resnet50', timeout,
                env=dict(layout_env, PADDLE_TPU_BN_COMPUTE='fp32'))
            if not err:
                ablations['resnet50_img_per_sec_bn_fp32'] = round(img_bn, 1)
                if img_s is not None and img_bn > img_s * 1.02:
                    ablations['resnet50_bn_winner'] = 'fp32'
                    img_s = img_bn  # headline takes the faster BN compute
                else:
                    ablations['resnet50_bn_winner'] = 'bf16'
        if alive() and not over_budget():
            tok_1d, err = run_rec(
                'transformer_single_dispatch', 'transformer', timeout,
                env={'BENCH_SINGLE_DISPATCH': '1'})
            if not err:
                ablations['transformer_tok_per_sec_single_dispatch'] = \
                    round(tok_1d, 1)
        if alive() and not over_budget(extra=150.0):
            # pipelined trainer loop (ISSUE 4): the host-fed sync vs
            # D=2/4 ablation — feed/h2d/fetch overlap measured e2e,
            # with the overlap fraction beside each throughput row
            pl, err = run_rec('pipeline_transformer',
                              'pipeline_transformer', timeout + 150)
            if not err:
                ablations['pipeline_transformer'] = pl
        if on_chip and alive() and not over_budget(extra=150.0):
            plr, err = run_rec('pipeline_resnet50', 'pipeline_resnet50',
                               timeout + 150)
            if not err:
                ablations['pipeline_resnet50'] = plr
        if on_chip and alive() and not over_budget(extra=timeout + 200.0):
            # seq-4096 e2e pair: the long-context claim measured, both
            # attention paths
            tok_4k, err = run_rec('transformer_seq4096',
                                  'transformer_seq4096', timeout + 100)
            if not err:
                ablations['transformer_tok_per_sec_seq4096'] = \
                    round(tok_4k, 1)
                if alive() and not over_budget(extra=100.0):
                    tok_4kp, err = run_rec(
                        'transformer_seq4096_pallas', 'transformer_seq4096',
                        timeout + 100, env={'PADDLE_TPU_USE_PALLAS': '1'})
                    if not err:
                        ablations['transformer_tok_per_sec_seq4096_pallas'] \
                            = round(tok_4kp, 1)
                        ablations['seq4096_attention_winner'] = \
                            'pallas' if tok_4kp > tok_4k * 1.02 else 'xla'
        if alive() and not over_budget(extra=150.0):
            # seq-256 compile (run_steps scan over a longer-attention
            # graph) can exceed the standard watchdog — give it slack
            tok_256, err = run_rec('transformer_seq256',
                                   'transformer_seq256', timeout + 150)
            if not err:
                ablations['transformer_tok_per_sec_seq256'] = round(tok_256,
                                                                    1)
        if alive() and not over_budget():
            tok_scan, err = run_rec(
                'transformer_scan_layers', 'transformer', timeout,
                env={'PADDLE_TPU_SCAN_LAYERS': '1'})
            if not err:
                ablations['transformer_tok_per_sec_scan_layers'] = \
                    round(tok_scan, 1)
        if on_chip and alive() and not over_budget():
            # default PRNG on TPU is now rbg (executor._default_prng);
            # this ablation records what threefry costs (on cpu the
            # default already IS threefry — nothing to compare)
            tok_tf, err = run_rec(
                'transformer_threefry', 'transformer', timeout,
                env={'PADDLE_TPU_PRNG': 'threefry2x32'})
            if not err:
                ablations['transformer_tok_per_sec_threefry_prng'] = \
                    round(tok_tf, 1)
        if on_chip and alive() and not over_budget():
            parity, err = run_rec('pallas_parity', 'pallas_parity',
                                  min(timeout, 150.0))
            if not err:
                ablations['pallas_parity_max_abs_err'] = parity

    # vs_baseline keeps its headline meaning (geomean speedup of the two
    # FULL-shape workloads vs the P100 baselines). Reduced shapes are a
    # different model — emit 0.0 rather than an incomparable number.
    ratios = []
    if tok_s is not None:
        ratios.append(tok_s / BASE_TRANSFORMER_TOK_S)
    if img_s is not None:
        ratios.append(img_s / BASE_RESNET_IMG_S)
    if ratios and not reduced:
        speedup = float(np.prod(ratios)) ** (1.0 / len(ratios))
    else:
        speedup = 0.0

    if tok_s is not None:
        metric, value, unit = ('transformer_base_train_tokens_per_sec',
                               tok_s, 'tokens/s')
    elif img_s is not None:
        metric, value, unit = ('resnet50_train_images_per_sec',
                               img_s, 'images/s')
    else:
        metric, value, unit = 'bench_failed', 0.0, 'n/a'

    detail = {'backend': backend,
              'backend_forced': bool(forced),
              'reduced_shapes': reduced,
              'baseline': {'resnet50': BASE_RESNET_IMG_S,
                           'transformer': BASE_TRANSFORMER_TOK_S}}
    if tok_s is not None:
        detail['transformer_tok_per_sec'] = round(tok_s, 1)
        if not reduced:
            # headline MFU estimate at the headline shapes (batch 64,
            # seq 64, vocab 32k) via the unified observe-backed path
            detail['transformer_mfu_est'] = round(
                transformer_mfu_est(tok_s), 4)
    if img_s is not None:
        detail['resnet50_img_per_sec'] = round(img_s, 1)
    if masked_head is not None:
        # co-headline: the masked variable-length NMT shape with MFU
        detail['masked_seq512'] = masked_head
    if ablations:
        detail['ablations'] = ablations
    if errors:
        detail['errors'] = errors
    # Store-backed salvage: any workload this run failed to capture (or
    # never reached) but a previous driver/watcher/builder run measured
    # on the chip is surfaced with its provenance + timestamp — the
    # resumable-queue contract's read side.
    try:
        prior = store_load()
        # anything THIS run didn't capture ok — failed, skipped after a
        # relay death, or never reached — falls back to the store
        missed = {k: r for k, r in prior.items() if k not in captured}
        if missed:
            detail['prior_onchip'] = {
                k: {'value': r.get('value'), 'ts': r.get('ts'),
                    'provenance': r.get('provenance'),
                    'backend': r.get('backend')}
                for k, r in missed.items()}
    except Exception:
        pass
    if backend == 'cpu' and degraded:
        # Relay outage at capture time (the round-3 failure mode): carry
        # the most recent full-shape on-chip capture, clearly labeled,
        # so the artifact still records the chip evidence + provenance.
        try:
            base = os.path.dirname(os.path.abspath(__file__))
            cap_path = None
            for name in ('BENCH_builder_r5_onchip.json',
                         'BENCH_builder_r4_onchip.json'):
                p = os.path.join(base, name)
                if os.path.exists(p):
                    cap_path = p
                    break
            with open(cap_path) as f:
                cap = json.load(f)
            detail['last_onchip_capture'] = {
                'provenance': 'builder-run full bench.py on the real '
                              'chip (most recent available capture); '
                              'file ' + os.path.basename(cap_path),
                'transformer_tok_per_sec':
                    cap['detail'].get('transformer_tok_per_sec'),
                'resnet50_img_per_sec':
                    cap['detail'].get('resnet50_img_per_sec'),
                'vs_baseline': cap.get('vs_baseline'),
            }
        except Exception:
            pass
        try:
            # watcher-captured workloads (tools/onchip_watcher.py drains
            # its queue whenever the relay flaps up): surface the ok
            # records so the artifact carries the freshest chip evidence
            wpath = os.path.join(os.path.dirname(os.path.abspath(
                __file__)), 'ONCHIP_r04.jsonl')
            if os.path.exists(wpath):
                ok = []
                with open(wpath) as f:
                    for ln in f:
                        # per-line: the watcher may append concurrently,
                        # and one torn line must not drop the rest
                        try:
                            r = json.loads(ln)
                        except ValueError:
                            continue
                        if r.get('ok'):
                            ok.append(r)
                if ok:
                    detail['watcher_onchip_results'] = {
                        r['workload']: r.get('results', [])[-3:]
                        for r in ok}
        except Exception:
            pass

    print(json.dumps({
        'metric': metric,
        'value': round(value, 1),
        'unit': unit,
        'vs_baseline': round(speedup, 3),
        'detail': detail,
    }))


# Every workload --workload accepts, at module level so the watcher
# QUEUE <-> argparse consistency test can import it (the PR 13 lesson:
# 'autoscale' was queued but not an accepted choice, and nothing
# noticed until the watcher drained on chip).
WORKLOAD_CHOICES = [
    'transformer', 'transformer_seq256', 'transformer_seq1024',
    'transformer_seq4096', 'transformer_big',
    'transformer_seq512_masked', 'rnn_lstm', 'resnet50',
    'resnet50_anatomy', 'attention_microbench', 'pallas_parity',
    'moe_cap1.0', 'moe_cap1.25', 'moe_cap2.0', 'pipeline_transformer',
    'pipeline_resnet50', 'decode_transformer', 'fleet', 'autoscale',
    'quant', 'disagg', 'linalg', 'autotune', 'autotune_child',
    'verify', 'crosshost', 'multitenant', 'trainspeed',
]

if __name__ == '__main__':
    if '--workload' in sys.argv:
        import argparse
        p = argparse.ArgumentParser()
        p.add_argument('--workload', choices=WORKLOAD_CHOICES)
        p.add_argument('--backend', default='cpu')
        p.add_argument('--reduced', action='store_true')
        a = p.parse_args()
        _run_workload_child(a.workload, a.backend, a.reduced)
    else:
        main()
