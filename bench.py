"""Headline benchmark (SURVEY.md §5). Trains the two BASELINE workloads on
the real chip and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baselines (BASELINE.json, reference-era P100 fp32 batch 64):
ResNet-50 ~200 img/s, Transformer base ~4500 tok/s. The headline metric is
the geometric-mean speedup over both; `value` is Transformer tok/s.
"""

import json
import time

import numpy as np

BASE_RESNET_IMG_S = 200.0
BASE_TRANSFORMER_TOK_S = 4500.0


def _fresh():
    import paddle_tpu as fluid
    fluid.reset_default_programs()
    fluid.global_scope().clear()
    return fluid


def _time_steps(run_step, warmup=3, iters=20):
    for _ in range(warmup):
        np.asarray(run_step()[0])  # np.asarray: the only true relay sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run_step()
    np.asarray(out[0])
    return (time.perf_counter() - t0) / iters


def _to_device(feed):
    import jax
    return {k: jax.device_put(v) for k, v in feed.items()}


def bench_transformer(batch=64, seq=64, vocab=32000):
    fluid = _fresh()
    from paddle_tpu.models import transformer as T
    avg_cost, _ = T.transformer_base(
        src_vocab_size=vocab, trg_vocab_size=vocab,
        src_seq_len=seq, trg_seq_len=seq, dropout_rate=0.1)
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    # Device-resident feed: real input pipelines prefetch to HBM
    # (reader.prefetch_to_device); the bench measures the train step.
    feed = _to_device(T.make_fake_batch(batch, seq, seq, vocab, vocab))

    def step():
        return exe.run(feed=feed, fetch_list=[avg_cost], return_numpy=False)

    dt = _time_steps(step)
    return batch * seq / dt


def bench_resnet50(batch=64):
    fluid = _fresh()
    from paddle_tpu.models.resnet import resnet50_with_loss
    _, avg_cost, _ = resnet50_with_loss()
    fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(
        avg_cost)
    fluid.default_main_program().amp = 'bf16'
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = _to_device(
        {'image': rng.rand(batch, 3, 224, 224).astype('float32'),
         'label': rng.randint(0, 1000, (batch, 1)).astype('int64')})

    def step():
        return exe.run(feed=feed, fetch_list=[avg_cost], return_numpy=False)

    dt = _time_steps(step)
    return batch / dt


def main():
    tok_s = bench_transformer()
    img_s = bench_resnet50()
    speedup = ((tok_s / BASE_TRANSFORMER_TOK_S) *
               (img_s / BASE_RESNET_IMG_S)) ** 0.5
    print(json.dumps({
        'metric': 'transformer_base_train_tokens_per_sec',
        'value': round(tok_s, 1),
        'unit': 'tokens/s',
        'vs_baseline': round(speedup, 3),
        'detail': {'resnet50_img_per_sec': round(img_s, 1),
                   'transformer_tok_per_sec': round(tok_s, 1),
                   'baseline': {'resnet50': BASE_RESNET_IMG_S,
                                'transformer': BASE_TRANSFORMER_TOK_S}},
    }))


if __name__ == '__main__':
    main()
