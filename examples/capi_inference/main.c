/* Serve a saved paddle_tpu model from plain C through the inference
 * C ABI (paddle_tpu/native/capi.h). Reference analog:
 * paddle/capi/examples/model_inference/dense/main.c.
 *
 * Usage: ./infer <model_dir>   (a dir from fluid.io.save_inference_model
 * whose feed is one float32 tensor named "x" of shape [batch, 13]) */
#include <stdio.h>
#include <stdlib.h>

#include "capi.h"

#define CHECK(expr)                                                     \
  do {                                                                  \
    paddle_error e_ = (expr);                                           \
    if (e_ != kPD_NO_ERROR) {                                           \
      fprintf(stderr, "%s -> %s: %s\n", #expr, paddle_error_string(e_), \
              paddle_last_error_message());                             \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <model_dir>\n", argv[0]);
    return 2;
  }
  CHECK(paddle_tpu_init(NULL)); /* NULL = auto backend; "cpu" forces CPU */

  paddle_predictor pred;
  CHECK(paddle_predictor_create(argv[1], &pred));

  float x[2 * 13];
  for (int i = 0; i < 2 * 13; i++) x[i] = 0.1f * (float)(i % 13);
  paddle_tensor in;
  in.dtype = PD_FLOAT32;
  in.ndim = 2;
  in.shape[0] = 2;
  in.shape[1] = 13;
  in.data = x;
  const char* names[] = {"x"};
  CHECK(paddle_predictor_run(pred, 1, names, &in));

  int32_t n;
  CHECK(paddle_predictor_output_count(pred, &n));
  for (int32_t i = 0; i < n; i++) {
    paddle_tensor out;
    CHECK(paddle_predictor_output(pred, i, &out));
    printf("output %d: shape [", i);
    for (int32_t d = 0; d < out.ndim; d++)
      printf("%s%lld", d ? ", " : "", (long long)out.shape[d]);
    printf("]  first value %.5f\n", ((const float*)out.data)[0]);
  }
  CHECK(paddle_predictor_destroy(pred));
  printf("OK\n");
  return 0;
}
