#!/bin/sh
# Build the C client against the embedded-runtime inference library and
# run it on a freshly saved fit_a_line model.
set -e
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
cd "$REPO/examples/capi_inference"
export PYTHONPATH="$REPO:$PYTHONPATH"

MODEL_DIR="$(mktemp -d)/model"
# PADDLE_TPU_CAPI_PLATFORM picks the C client's backend; the same value
# drives the model-saving python below (in-script config update — the
# reliable way to pick a backend before any device query)
PLATFORM="${PADDLE_TPU_CAPI_PLATFORM:-cpu}"
export PADDLE_TPU_CAPI_PLATFORM="$PLATFORM"
python - "$MODEL_DIR" "$PLATFORM" <<'EOF'
import sys
import jax
jax.config.update('jax_platforms', sys.argv[2])
import numpy as np
import paddle_tpu as fluid

x = fluid.layers.data(name='x', shape=[13], dtype='float32')
pred = fluid.layers.fc(input=x, size=1)
exe = fluid.Executor(fluid.TPUPlace(0))
exe.run(fluid.default_startup_program())
fluid.io.save_inference_model(sys.argv[1], ['x'], [pred], exe)
print('saved', sys.argv[1])
EOF

SO="$(python -c 'from paddle_tpu.native import build_capi; print(build_capi())')"
cc main.c -I "$REPO/paddle_tpu/native" "$SO" \
   -Wl,-rpath,"$(dirname "$SO")" -o infer
./infer "$MODEL_DIR"
