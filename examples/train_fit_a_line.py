"""Minimal end-to-end: linear regression on the uci_housing schema,
then save + reload an inference model (the fit_a_line book chapter)."""

import numpy as np

import paddle_tpu as fluid


def main():
    x = fluid.layers.data(name='x', shape=[13], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    w_true = rng.randn(13, 1).astype('float32')
    for step in range(200):
        xs = rng.randn(32, 13).astype('float32')
        ys = xs @ w_true + 0.5
        loss, = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[cost])
        if step % 50 == 0:
            print('step %3d  loss %.6f' % (step, float(np.asarray(loss).reshape(()))))

    fluid.io.save_inference_model('/tmp/fit_a_line_model', ['x'], [pred],
                                  exe)
    prog, feeds, fetches = fluid.io.load_inference_model(
        '/tmp/fit_a_line_model', exe)
    xs = rng.randn(4, 13).astype('float32')
    out = exe.run(program=prog, feed={'x': xs}, fetch_list=fetches)
    err = np.abs(np.asarray(out[0]) - (xs @ w_true + 0.5)).max()
    print('reloaded model max abs err vs truth: %.4f' % err)


if __name__ == '__main__':
    main()
