"""The large-model recipe: a scan-stacked transformer trained over a
dp x pp x tp mesh. The model is built once with scan_layers=True (one
transformer_layer_stack op per side); ParallelStrategy(
pipeline_parallel=True, tensor_parallel=True) stage-shards the layer
stacks over 'pp' and Megatron-splits the matmul weights over 'tp', and
Executor.run trains exactly as on one device — the GPipe schedule and
all collectives live inside the jitted step.

Runs on 8 virtual CPU devices by default; on a real 8-chip slice,
remove the force_host_cpu call.
"""

import numpy as np


def main():
    from paddle_tpu.core.platform_boot import force_host_cpu
    force_host_cpu(8)   # drop this line on real hardware

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.transpiler import ParallelStrategy, transpile

    avg_cost, _ = T.transformer_base(
        src_vocab_size=1024, trg_vocab_size=1024,
        src_seq_len=32, trg_seq_len=32,
        n_layer=4, d_model=64, d_inner=256, d_key=16, d_value=16,
        dropout_rate=0.1, scan_layers=True)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)

    mesh = make_mesh(dp=2, pp=2, tp=2)
    transpile(fluid.default_main_program(), mesh,
              ParallelStrategy(data_parallel=True, tensor_parallel=True,
                               pipeline_parallel=True,
                               pipeline_microbatches=2))

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    for step in range(10):
        feed = T.make_fake_batch(8, 32, 32, 1024, 1024, seed=step)
        loss, = exe.run(feed=feed, fetch_list=[avg_cost])
        print('step %d  loss %.4f' % (step, float(np.asarray(loss).reshape(()))))


if __name__ == '__main__':
    main()
