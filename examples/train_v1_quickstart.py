"""The reference quick_start demo, ported to the v1 compat shim.

Reference analog: demo/quick_start (v1-era text classification:
embedding -> sequence conv-pool -> softmax fc, configured through
trainer_config_helpers). The ONLY change a legacy config needs is the
import line — every helper below builds fluid IR eagerly and the whole
model jits to one XLA computation (see
paddle_tpu/trainer_config_helpers/layers.py for the divergence notes).

Run: PYTHONPATH=/path/to/repo:$PYTHONPATH python examples/train_v1_quickstart.py
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.trainer_config_helpers import (
    AdamOptimizer, L2Regularization, SoftmaxActivation, classification_cost,
    data_layer, embedding_layer, fc_layer, sequence_conv_pool, settings)

VOCAB, SEQ, BATCH = 1000, 32, 64

# ---- config (the part that was a v1 trainer_config file) ----
words = data_layer(name='words', size=VOCAB, dtype='int64', seq_type=1)
label = data_layer(name='label', size=1, dtype='int64')
emb = embedding_layer(input=words, size=64)
conv = sequence_conv_pool(input=emb, context_len=3, hidden_size=128)
prob = fc_layer(input=conv, size=2, act=SoftmaxActivation())
cost = classification_cost(input=prob, label=label)
settings(batch_size=BATCH, learning_rate=5e-3,
         learning_method=AdamOptimizer(),
         regularization=L2Regularization(1e-5)).minimize(cost)

# ---- train loop (the part the v1 trainer binary used to own) ----
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
rng = np.random.RandomState(0)


def synth_batch():
    ws = rng.randint(1, VOCAB, (BATCH, SEQ)).astype('int64')
    lens = rng.randint(SEQ // 2, SEQ + 1, (BATCH,)).astype('int32')
    # learnable rule, balanced classes: does any token from the rare
    # "positive" band (id < 21) appear among the UNPADDED positions?
    # (1 - 21/999)^32 ~= 0.5 so labels split ~50/50, presence detection
    # is exactly what conv + max-pool expresses, and masking the padded
    # tail keeps the rule fully visible to the model.
    visible = np.arange(SEQ)[None, :] < lens[:, None]
    ys = ((ws < 21) & visible).any(1).astype('int64')[:, None]
    return {'words': ws, 'words_len': lens, 'label': ys}


for step in range(400):
    loss, = exe.run(feed=synth_batch(), fetch_list=[cost])
    if step % 80 == 0:
        print('step %3d  loss %.4f' % (step, float(np.asarray(loss))))
print('final loss %.4f' % float(np.asarray(loss)))
