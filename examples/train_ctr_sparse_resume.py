"""CTR-scale training: a 1M-row embedding with row-sparse gradients
(is_sparse=True + SGD — per-step grad memory is O(batch x dim), the
SelectedRows role) fed by a CheckpointableReader, checkpointed
mid-epoch and resumed with exactly the untrained remainder."""

import os
import tempfile

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import reader as R


def build():
    fluid.reset_default_programs()
    ids = fluid.layers.data(name='ids', shape=[8], dtype='int64')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    emb = fluid.layers.embedding(input=ids, size=[1_000_000, 16],
                                 is_sparse=True)
    pooled = fluid.layers.reduce_sum(emb, dim=1)
    pred = fluid.layers.fc(input=pooled, size=1)
    cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    return exe, cost


def batches(n, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        yield {'ids': rng.randint(0, 1_000_000, (64, 8)).astype('int64'),
               'y': rng.rand(64, 1).astype('float32')}


def main():
    ckpt = os.path.join(tempfile.mkdtemp(), 'ckpt')
    exe, cost = build()
    reader = R.CheckpointableReader(lambda: batches(20), shuffle_buf=8,
                                    seed=42)

    # train 12 of 20 batches, then "crash"
    gen = reader()
    for i, b in enumerate(gen):
        loss, = exe.run(feed=b, fetch_list=[cost])
        if i == 11:
            break
    gen.close()
    fluid.io.save_checkpoint(exe, ckpt, step=12, reader=reader)
    print('checkpointed mid-epoch after 12 batches, loss %.4f'
          % float(np.asarray(loss).reshape(())))

    # fresh process: params + reader position restored
    fluid.global_scope().clear()
    exe, cost = build()
    reader2 = R.CheckpointableReader(lambda: batches(20), shuffle_buf=8,
                                     seed=42)
    step = fluid.io.load_checkpoint(exe, ckpt, reader=reader2)
    rest = list(reader2())
    print('resumed at step %d; epoch remainder: %d batches (expect 8)'
          % (step, len(rest)))
    for b in rest:
        loss, = exe.run(feed=b, fetch_list=[cost])
    print('epoch finished, loss %.4f' % float(np.asarray(loss).reshape(())))


if __name__ == '__main__':
    main()
