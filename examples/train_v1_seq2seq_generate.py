"""The seqToseq demo's shape through the v1 compat shim: an attention
encoder-decoder built with `recurrent_group` + `memory`, trained on a
copy task, then BEAM GENERATION via `beam_search` with `StaticInput`
and `GeneratedInput` feedback.

Reference analog: demo/seqToseq (seqToseq_net.py's gru_decoder_with
_attention + the gen.conf beam config, built on
trainer_config_helpers/layers.py:4082 recurrent_group, :4215
GeneratedInput, :4406 beam_search). The ONLY change a legacy config
needs is the import line. TPU-native difference: the step function
traces ONCE into a lax.scan (training) and the whole beam generation —
feedback, expansion, pruning, backtrack — compiles into one XLA
program (ops/rnn_ops.py generation_decode) instead of the reference's
per-token step-net re-runs.

Run: PYTHONPATH=/path/to/repo:$PYTHONPATH \
     python examples/train_v1_seq2seq_generate.py
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.program import Program, program_guard
from paddle_tpu.trainer_config_helpers import (
    AdamOptimizer, GeneratedInput, ParameterAttribute, SoftmaxActivation,
    StaticInput, TanhActivation, beam_search, classification_cost,
    data_layer, embedding_layer, fc_layer, gru_step_layer, last_seq,
    memory, recurrent_group, settings, simple_attention, simple_gru)

VOCAB, EMB, HIDDEN, SEQ, BATCH = 30, 16, 24, 6, 16
BOS, EOS = 1, 0


def encoder(src_name='src'):
    src = data_layer(name=src_name, size=VOCAB, dtype='int64', seq_type=1)
    emb = embedding_layer(input=src, size=EMB,
                          param_attr=ParameterAttribute(name='src_emb'))
    enc = simple_gru(input=emb, size=HIDDEN,
                     mixed_param_attr=ParameterAttribute(name='enc_mix.w'),
                     gru_param_attr=ParameterAttribute(name='enc_gru.w'),
                     gru_bias_attr=ParameterAttribute(name='enc_gru.b'))
    boot = fc_layer(input=last_seq(input=enc), size=HIDDEN,
                    act=TanhActivation(),
                    param_attr=ParameterAttribute(name='boot.w'),
                    bias_attr=ParameterAttribute(name='boot.b'))
    enc_proj = fc_layer(input=enc, size=HIDDEN, bias_attr=False,
                        param_attr=ParameterAttribute(name='enc_proj.w'))
    return enc, enc_proj, boot


def decoder_step(emb, state, enc, enc_proj):
    """The shared step math — reference gru_decoder_with_attention."""
    context = simple_attention(
        encoded_sequence=enc, encoded_proj=enc_proj, decoder_state=state,
        transform_param_attr=ParameterAttribute(name='att_trans.w'),
        softmax_param_attr=ParameterAttribute(name='att_score.w'))
    x = fc_layer(input=[emb, context], size=HIDDEN * 3, bias_attr=False,
                 param_attr=ParameterAttribute(name='dec_proj.w'))
    new_state = gru_step_layer(
        input=x, output_mem=state, name='dec_state',
        param_attr=ParameterAttribute(name='dec_gru.w'),
        bias_attr=ParameterAttribute(name='dec_gru.b'))
    return fc_layer(input=new_state, size=VOCAB, act=SoftmaxActivation(),
                    param_attr=ParameterAttribute(name='dec_out.w'),
                    bias_attr=ParameterAttribute(name='dec_out.b'))


def main():
    # ---------------- training graph (teacher forced)
    enc, enc_proj, boot = encoder()
    trg = data_layer(name='trg', size=VOCAB, dtype='int64', seq_type=1)
    trg_emb = embedding_layer(
        input=trg, size=EMB, param_attr=ParameterAttribute(name='trg_emb'))
    lbl = data_layer(name='lbl', size=1, dtype='int64', seq_type=1)

    def train_step(emb_t):
        state = memory(name='dec_state', size=HIDDEN, boot_layer=boot)
        return decoder_step(emb_t, state, enc, enc_proj)

    probs = recurrent_group(step=train_step, input=trg_emb)
    cost = classification_cost(input=probs, label=lbl)
    settings(learning_rate=8e-3,
             learning_method=AdamOptimizer()).minimize(cost)

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    src = rng.randint(2, VOCAB, (BATCH, SEQ)).astype('int64')
    trg_in = np.concatenate([np.full((BATCH, 1), BOS, 'int64'),
                             src[:, :-1]], axis=1)
    feed = {'src': src, 'src_len': np.full((BATCH,), SEQ, 'int32'),
            'trg': trg_in, 'trg_len': np.full((BATCH,), SEQ, 'int32'),
            'lbl': src[..., None], 'lbl_len': np.full((BATCH,), SEQ,
                                                      'int32')}
    for i in range(200):
        loss, = exe.run(feed=feed, fetch_list=[cost])
        if i % 50 == 0:
            print('step %d loss %.4f'
                  % (i, float(np.asarray(loss).reshape(()))))

    # ---------------- beam generation (params shared by name)
    gen_program = Program()
    with program_guard(gen_program, fluid.default_startup_program()):
        enc_g, proj_g, boot_g = encoder(src_name='src')

        def gen_step(enc_s, proj_s, boot_s, emb):
            state = memory(name='dec_state', size=HIDDEN,
                           boot_layer=boot_s)
            return decoder_step(emb, state, enc_s, proj_s)

        ids = beam_search(
            step=gen_step,
            input=[StaticInput(enc_g, is_seq=True), StaticInput(proj_g),
                   StaticInput(boot_g),
                   GeneratedInput(size=VOCAB, embedding_name='trg_emb',
                                  embedding_size=EMB)],
            bos_id=BOS, eos_id=EOS, beam_size=4, max_length=SEQ)

    out = exe.run(program=gen_program,
                  feed={'src': src,
                        'src_len': np.full((BATCH,), SEQ, 'int32')},
                  fetch_list=[ids, ids._beam_scores])
    beams, scores = (np.asarray(v) for v in out)
    acc = (beams[:, 0, :] == src).mean()
    print('top-beam copy accuracy: %.2f' % acc)
    print('example: src %s -> gen %s (score %.2f)'
          % (src[0].tolist(), beams[0, 0].tolist(), scores[0, 0]))
    assert acc > 0.8, 'beam generation failed to reproduce the copy task'


if __name__ == '__main__':
    main()
